"""Multi-chip execution: hosts sharded over a jax.sharding.Mesh axis.

This is the TPU-native replacement for the reference's host→thread
assignment and barrier machinery (ref: scheduler.c:437-531 host
shuffling; scheduler.c:359-414 + master.c:450-480 round barriers):

- Host rows (event queues, socket tables, NIC state) shard over the
  mesh's host axis; global lookup tables (IP maps, the dense
  latency/reliability matrices) replicate.
- The window fixpoint is purely shard-local — each chip drains its own
  hosts' events at its own pace, no communication (the analog of
  worker threads running between barriers).
- The only collectives, once per window: an all-to-all exchanging
  cross-shard events staged in the outbox (the analog of
  scheduler_push to another thread's queue, scheduler.c:339-357), and
  a pmin over per-shard next-event times (the analog of the
  executeEvents barrier + min reduction, scheduler.c:393-398). Both
  ride ICI on a real TPU mesh.

Determinism: event identity is (time, dst, src, per-source seq) and
pop order is a lexicographic argmin over those keys (events.py), so
results are bit-identical for any shard count — the same property the
reference gets from its 4-key event sort (ref: event.c:110-153).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.tree_util import tree_map_with_path

try:                                  # jax >= 0.6 top-level name
    from jax import shard_map as _shard_map
except ImportError:                   # 0.4.x: experimental home, and the
    # replication-check kwarg is still called check_rep there
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def _shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import (
    EngineStats,
    resolve_sparse_lanes,
    run as engine_run,
)
from shadow_tpu.core.events import (
    EventQueue,
    Outbox,
    _pack_time,
    _unpack_time,
    clear_outbox,
    insert_flat,
    segment_ranks,
)
from shadow_tpu.net.state import NetState, REPLICATED_FIELDS
from shadow_tpu.parallel.elastic import make_sentinel_fn
from shadow_tpu.telemetry.flows import make_flow_fn
from shadow_tpu.telemetry.ring import make_telem_fn

I32 = jnp.int32


def sim_specs(sim, axis: str):
    """PartitionSpec pytree for a Sim (or any engine-compatible state):
    NetState's replicated lookup tables and scalar leaves get P();
    everything else shards its leading (host) dimension over `axis`.
    App states must follow the same convention: leading-H arrays or
    scalars."""

    def spec(path, leaf):
        names = [k.name for k in path if hasattr(k, "name")]
        # The telemetry ring is replicated state: its [W] planes are
        # ring slots, not host rows, and every value stored is already
        # globally reduced at the window barrier (telemetry/ring.py).
        # This check must come first — the 1-D planes would otherwise
        # fall through to P(axis). The injection staging buffer is
        # replicated the same way: every shard sees every staged
        # event and merges only the rows it owns (inject/staging.py).
        # The lane-health latches (core/lanes.py) are [R] lane planes,
        # also not host rows — but their window_update reduces
        # shard-LOCAL host planes, so lane isolation is a
        # single-shard feature today (enforced by the attach sites).
        # The flow ring (telemetry/flows.py) is replicated like telem:
        # its [F] planes are ring slots holding globally-merged
        # records, identical on every shard after the barrier psum.
        if names and names[0] in ("telem", "inject", "lanes", "flows"):
            return P()
        # Causality state (telemetry/causality.py) is mixed: the
        # lineage sub-rings are per-HOST rows ([H, F] planes and [H]
        # counters — appends are row-local, so they shard like event
        # queues), while the advance-attribution plane (adv_* leaves)
        # is latched from replicated window values on every shard and
        # replicates like the telemetry ring.
        if names and names[0] == "causality":
            if names[-1].startswith("adv_") or jnp.ndim(leaf) == 0:
                return P()
            return P(axis)
        # Replicated lookup tables are identified by NetState field
        # name, scoped to the NetState subtree ("net" in a Sim, or a
        # bare NetState) so an app field that happens to share a name
        # still shards.
        if names and names[-1] in REPLICATED_FIELDS and (
            names[-2] == "net" if len(names) > 1
            else isinstance(sim, NetState)
        ):
            return P()
        if jnp.ndim(leaf) == 0:
            return P()
        return P(axis)

    return tree_map_with_path(spec, sim)


def route_outbox_sharded(
    q: EventQueue, out: Outbox, axis: str, num_shards: int,
    lane_id: jax.Array, exchange_capacity: int | None = None,
    narrow: int | None = None,
) -> tuple[EventQueue, Outbox]:
    """Exchange staged cross-host events across shards and insert them
    into destination rows — the window-boundary all-to-all of
    (dst, time, kind, src, seq, words) records (SURVEY.md §5.8).

    Each shard owns the contiguous global host range
    [lane_id[0], lane_id[0] + Hl); an event's target shard is
    dst // Hl. Entries are grouped per target shard by a stable sort,
    exchanged with lax.all_to_all, then inserted with the same
    insert_flat as the single-shard path, in the same global
    (source row, emission slot) order — so the resulting queue state is
    bit-identical to the single-shard route.

    exchange_capacity bounds the per-peer exchange buffer (default:
    the whole outbox, Hl*M, which can never overflow). Smaller values
    cut ICI transfer ~linearly; entries beyond the cap are counted in
    q.overflow, never silently dropped.

    The narrow tier (r4, the sharded analog of events.ROUTE_NARROW):
    the worst-case buffer is sized for one shard sending its WHOLE
    outbox to one peer, but a steady-state window spreads far fewer
    events across peers — so both the collective payload and the
    receive-side insert (which scale with num_shards * C) run at a
    narrow capacity whenever the LARGEST per-target group fits it,
    decided by a scalar pmax so every shard takes the same branch.
    Entries never drop: oversize windows take the full-width branch."""
    Hl, M = out.dst.shape
    GH = Hl * num_shards
    base = lane_id[0]
    n = Hl * M
    C_full = n if exchange_capacity is None else min(exchange_capacity, n)

    dst = out.dst.reshape(n)
    occupied = dst >= 0
    bad = occupied & (dst >= GH)
    valid = occupied & ~bad
    tgt = jnp.where(valid, dst // Hl, num_shards)

    # group by target shard (stable keeps global source order)
    order = jnp.argsort(tgt, stable=True)
    tgt_s = tgt[order]
    ok = tgt_s < num_shards
    rank = segment_ranks(tgt_s)

    # Pack EVERY plane — the i64 time split into two i32 words — into
    # one buffer so the per-window exchange is exactly ONE collective
    # instead of six; each all_to_all pays its ICI launch latency once
    # per window (VERDICT r3 #4). Unwritten slots must read dst == -1
    # (empty), so the dst plane's fill is -1.
    W = out.words.shape[-1]
    t_lo, t_hi = _pack_time(out.time)
    packed = jnp.concatenate(
        [out.dst[..., None], t_lo[..., None], t_hi[..., None],
         out.kind[..., None], out.src[..., None], out.seq[..., None],
         out.words], axis=2,
    )  # [Hl, M, 6+W]
    flat = packed.reshape(n, 6 + W)[order]

    def exchange(qq, C):
        fits = ok & (rank < C)
        xofl = jnp.sum(ok & ~fits, dtype=I32)
        row = jnp.where(fits, tgt_s, num_shards)
        slot = jnp.where(fits, rank, C)
        sb_i32 = jnp.zeros((num_shards, C, 6 + W), I32).at[..., 0].set(-1)
        sb_i32 = sb_i32.at[row, slot].set(flat, mode="drop")

        a2a = partial(lax.all_to_all, axis_name=axis, split_axis=0,
                      concat_axis=0)
        rb_i32 = a2a(sb_i32)

        nn = num_shards * C
        ri32 = rb_i32.reshape(nn, 6 + W)
        rdst = ri32[:, 0]
        rtime = _unpack_time(ri32[:, 1], ri32[:, 2])
        occupied_r = rdst >= 0
        local_row = rdst - base
        # An arriving dst outside this shard's [base, base+Hl) block
        # means the lane assignment violated the contiguous-block
        # contract — count it loudly (a negative row would otherwise
        # wrap-around write; an oversized one would be silently
        # dropped).
        misrouted = occupied_r & ((local_row < 0) | (local_row >= Hl))
        rvalid = occupied_r & ~misrouted
        qq = insert_flat(
            qq, rvalid, jnp.where(rvalid, local_row, Hl),
            rtime, ri32[:, 3], ri32[:, 4],
            ri32[:, 5], ri32[:, 6:],
        )
        return qq.replace(
            overflow=qq.overflow + jnp.sum(bad, dtype=I32) + xofl
            + jnp.sum(misrouted, dtype=I32))

    C_n = (max(M, n // (4 * num_shards)) if narrow is None
           else narrow)
    # +1 so rank == C_n-1 fits; a globally empty exchange gives
    # gmax == 0 — the common case in sparse windows, where the whole
    # all-to-all + insert pipeline is elided (layer 3). The pmax'd
    # predicate is identical on every shard, so skipping the
    # collective is coherent (the narrow-tier precedent).
    gmax = lax.pmax(jnp.max(jnp.where(ok, rank, -1)) + 1, axis)
    empty = gmax == 0

    def elide(qq):
        # bad-dst entries are excluded from `ok` (they never enter the
        # exchange) but still owe their loud overflow accounting
        return qq.replace(overflow=qq.overflow + jnp.sum(bad, dtype=I32))

    if C_n and C_n < C_full:
        hit = gmax <= C_n
        out = out.replace(
            narrow_hit=out.narrow_hit + hit.astype(I32),
            narrow_miss=out.narrow_miss + (~hit).astype(I32),
            max_occupied=jnp.maximum(out.max_occupied,
                                     gmax.astype(I32)),
            route_elided=out.route_elided + empty.astype(I32))
        q = lax.cond(
            empty,
            elide,
            lambda qq: lax.cond(
                hit,
                lambda q2: exchange(q2, C_n),
                lambda q2: exchange(q2, C_full),
                qq),
            q)
    else:
        out = out.replace(
            route_elided=out.route_elided + empty.astype(I32))
        q = lax.cond(empty, elide, lambda qq: exchange(qq, C_full), q)
    return q, clear_outbox(out)


def _replicate_scalars(sim, initial_sim, stats: EngineStats, axis: str):
    """psum EVERY scalar leaf's *delta* over the run so out_specs can
    declare them replicated — scalar leaves are per-shard partial
    counters by convention (overflow/drop totals); a new counter added
    anywhere in the state tree is aggregated automatically instead of
    silently returning one shard's value. The delta (not the value) is
    summed because the initial value is replicated on every shard —
    psumming it directly would multiply a nonzero starting count by the
    shard count. stats.windows is identical on every shard (lockstep
    outer loop), so pmax is the identity there."""
    # the narrow-tier telemetry is pmax'd, not delta-psummed: the
    # exchange gate's own pmax makes the branch (and so hit/miss)
    # identical on every shard, and a sum of per-shard maxima would be
    # meaningless for max_occupied — pin all three, overwrite after.
    ob = sim.outbox
    # route_elided rides along: the elision branch is decided by a
    # pmax'd census, so the count is already identical on every shard.
    narrow_pinned = (lax.pmax(ob.narrow_hit, axis),
                     lax.pmax(ob.narrow_miss, axis),
                     lax.pmax(ob.max_occupied, axis),
                     lax.pmax(ob.route_elided, axis))
    # The telemetry ring is pinned the same way: its scalars (count,
    # prev_*) and planes already hold globally-reduced values — the
    # delta-psum below would multiply them by the shard count.
    telem = getattr(sim, "telem", None)
    # The flow ring's planes and scalars are likewise already
    # globally merged at the barrier (telemetry/flows.py) — pin.
    flows = getattr(sim, "flows", None)
    # Injection staging: seq_floor and horizon are REPLICATED values
    # (the floor advance is the same pure function of the replicated
    # planes on every shard) — the delta-psum would multiply the
    # advance by the shard count. Pin both; the cumulative counters
    # (injected/dropped/late) are per-shard partials and take the
    # generic delta-psum below like every other counter.
    inject = getattr(sim, "inject", None)
    # Causality's only scalar, adv_count, is REPLICATED (every shard
    # latches the same windows into the same slots) — the delta-psum
    # would multiply it by the shard count. The [H]/[H,F] lineage
    # leaves and [W] adv planes are non-scalar and untouched below.
    caus = getattr(sim, "causality", None)
    # The integrity sentinel's leaves are all replicated scalars —
    # every update is a pure function of collectives
    # (parallel/elastic.py make_sentinel_fn) — so the subtree pins
    # like the telemetry ring.
    sentinel = getattr(sim, "sentinel", None)
    # The per-path matrix is declared replicated (REPLICATED_FIELDS)
    # but each shard scatter-adds only its own hosts' sends into its
    # replica — psum the [V,V] delta so the reassembled matrix equals
    # the serial one. Skipped when track_paths is off (the [1,1] zero
    # matrix needs no collective).
    net = getattr(sim, "net", None)
    path_pinned = None
    if net is not None and net.ctr_path_packets.shape != (1, 1):
        init_paths = initial_sim.net.ctr_path_packets
        path_pinned = init_paths + lax.psum(
            net.ctr_path_packets - init_paths, axis)
    sim = jax.tree.map(
        lambda leaf, init: init + lax.psum(leaf - init, axis)
        if jnp.ndim(leaf) == 0 else leaf,
        sim, initial_sim,
    )
    sim = sim.replace(outbox=sim.outbox.replace(
        narrow_hit=narrow_pinned[0], narrow_miss=narrow_pinned[1],
        max_occupied=narrow_pinned[2], route_elided=narrow_pinned[3]))
    if telem is not None:
        sim = sim.replace(telem=telem)
    if flows is not None:
        sim = sim.replace(flows=flows)
    if inject is not None:
        sim = sim.replace(inject=sim.inject.replace(
            seq_floor=inject.seq_floor, horizon=inject.horizon))
    if caus is not None:
        sim = sim.replace(causality=sim.causality.replace(
            adv_count=caus.adv_count))
    if sentinel is not None:
        sim = sim.replace(sentinel=sentinel)
    if path_pinned is not None:
        sim = sim.replace(net=sim.net.replace(
            ctr_path_packets=path_pinned))
    stats = EngineStats(
        events_processed=lax.psum(stats.events_processed, axis),
        micro_steps=lax.psum(stats.micro_steps, axis),
        windows=lax.pmax(stats.windows, axis),
        # the fastpath branch is globally decided (census_fn psum), so
        # every shard counted the same hits/misses — pin, don't sum
        fastpath_hit=lax.pmax(stats.fastpath_hit, axis),
        fastpath_miss=lax.pmax(stats.fastpath_miss, axis),
    )
    return sim, stats


def _harness_specs(mesh: Mesh, axis: str, sim):
    """Shared shard_map harness pieces: divisibility check + Sim and
    stats PartitionSpecs (used by both the whole-run and per-window
    wrappers — keep them identical)."""
    num_shards = mesh.shape[axis]
    H = sim.events.num_hosts
    if H % num_shards != 0:
        raise ValueError(f"num_hosts={H} not divisible by {num_shards} shards")
    specs = sim_specs(sim, axis)
    stats_specs = EngineStats(
        events_processed=P(), micro_steps=P(), windows=P(),
        fastpath_hit=P(), fastpath_miss=P(),
    )
    return num_shards, specs, stats_specs


def _sharded_route_fn(axis: str, num_shards: int, lane,
                      exchange_capacity: int | None,
                      narrow: int | None = None):
    """The window-boundary all-to-all as an engine route_fn."""
    def route(s):
        q, out = route_outbox_sharded(s.events, s.outbox, axis, num_shards,
                                      lane, exchange_capacity, narrow)
        return s.replace(events=q, outbox=out)
    return route


def _make_whole_run(mesh: Mesh, axis: str, sim, step_fn, *,
                    end_time: int, min_jump: int, emit_capacity: int,
                    lane_id_fn=None, exchange_capacity: int | None = None,
                    narrow: int | None = None,
                    bulk_fn=None, fault_fn=None, sparse_lanes: int = 0,
                    fault_times=None, warm_key=None,
                    warm_start: bool | None = None,
                    compile_info: dict | None = None):
    """Shared factory: a jitted sim -> (sim, stats) running the full
    engine loop under shard_map (used by sharded_engine_run and
    make_sharded_runner — keep their semantics identical).

    `warm_key` (a program key or a lazy (args, kwargs) -> key rule,
    compile/buckets.py) routes the jitted program through the
    persistent AOT store when `warm_start`/SHADOW_WARM_PROGRAMS says
    so — callers that know the bundle derive the key
    (net.build._whole_run_key_fn); without one, serving stays off
    (this factory only sees opaque closures it cannot key)."""
    num_shards, specs, stats_specs = _harness_specs(mesh, axis, sim)

    def _body(local_sim):
        lane = (lane_id_fn(local_sim) if lane_id_fn is not None
                else local_sim.net.lane_id)
        out_sim, stats = engine_run(
            local_sim,
            step_fn,
            end_time=end_time,
            min_jump=min_jump,
            emit_capacity=emit_capacity,
            lane_id=lane,
            route_fn=_sharded_route_fn(axis, num_shards, lane,
                                       exchange_capacity, narrow),
            min_fn=lambda x: lax.pmin(x, axis),
            bulk_fn=bulk_fn,
            # fault_fn closes over replicated plan constants and
            # derives everything from wend, which the pmin barrier
            # keeps identical on every shard — so each chip rewrites
            # the replicated tables to the same values with no extra
            # collective (faults/apply.py).
            fault_fn=fault_fn,
            # trace-time no-op when sim.telem is None (telemetry off)
            telem_fn=make_telem_fn(axis),
            # likewise a no-op when sim.flows is None (flow tracing off)
            flow_fn=make_flow_fn(axis),
            sparse_lanes=sparse_lanes,
            # the active-lane census is a GLOBAL count so every shard
            # takes the same compact/full branch
            census_fn=lambda x: lax.psum(x, axis),
            # the record-time wend clamp is computed from replicated
            # constants + the lockstep wstart, so it is shard-invariant
            fault_times=fault_times,
            # trace-time no-op when sim.sentinel is None (sentinel off)
            sentinel_fn=make_sentinel_fn(axis),
        )
        return _replicate_scalars(out_sim, local_sim, stats, axis)

    # check_vma=False: the engine's while_loop carries mix varying and
    # replicated leaves, which static VMA checking rejects without
    # pvary annotations throughout; replication of the declared-P()
    # outputs is guaranteed by _replicate_scalars psumming every
    # scalar leaf (and verified by the bit-identity tests).
    shmapped = _shard_map(
        _body, mesh=mesh, in_specs=(specs,), out_specs=(specs, stats_specs),
        check_vma=False,
    )
    from shadow_tpu.compile import serve

    jitted = serve.maybe_warm(
        jax.jit(shmapped), warm_key,
        enabled=serve.warm_enabled(default=bool(warm_start)),
        info=compile_info)
    in_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                                is_leaf=lambda x: isinstance(x, P))

    def go(s):
        return jitted(jax.device_put(s, in_shardings))

    return go


def sharded_engine_run(
    mesh: Mesh,
    axis: str,
    sim,
    step_fn,
    *,
    end_time: int,
    min_jump: int,
    emit_capacity: int = 4,
    lane_id_fn=None,
    exchange_capacity: int | None = None,
    narrow: int | None = None,
    bulk_fn=None,
    fault_fn=None,
    sparse_lanes: int = 0,
    fault_times=None,
):
    """shard_map the full engine.run over `mesh[axis]`. `sim` is the
    *global* state (as built for single-shard); sharding/replication
    follows sim_specs. lane_id_fn(local_sim) must return the [Hl]
    global host ids of the shard's rows (defaults to sim.net.lane_id).

    Returns (sim, stats) with global arrays reassembled."""
    return _make_whole_run(
        mesh, axis, sim, step_fn, end_time=end_time, min_jump=min_jump,
        emit_capacity=emit_capacity, lane_id_fn=lane_id_fn,
        exchange_capacity=exchange_capacity, narrow=narrow,
        bulk_fn=bulk_fn, fault_fn=fault_fn,
        sparse_lanes=sparse_lanes, fault_times=fault_times)(sim)


def make_sharded_window(mesh: Mesh, axis: str, sim_template, cfg, step_fn,
                        exchange_capacity: int | None = None,
                        narrow: int | None = None, bulk_fn=None,
                        fault_fn=None, donate: bool = False):
    """A jitted (sim, wstart, wend) -> (sim, stats, next_min) running
    ONE window round under shard_map — the building block for
    host-driven window loops (ProcessRuntime, checkpoint.run_windows)
    on a mesh. next_min is replicated by the pmin barrier; `sim` may be
    passed unsharded on first call (jit reshards per sim_specs). The
    telemetry hook is threaded with the mesh axis so ring aggregates
    are globally reduced — a trace-time no-op when sim.telem is None,
    exactly like the whole-run harness.

    `donate=True` donates the sim argument's buffers to the call
    (steady-state device allocation stays one sim across a long window
    loop). Opt-in: callers that re-read the input sim after dispatch —
    or pass the same sim twice (retry paths) — must leave it off."""
    from shadow_tpu.core.engine import step_window

    num_shards, specs, stats_specs = _harness_specs(mesh, axis,
                                                    sim_template)

    def _body(local_sim, wstart, wend):
        lane = local_sim.net.lane_id
        stats = EngineStats.create()
        out_sim, stats, next_min = step_window(
            local_sim, stats, step_fn, wend,
            emit_capacity=cfg.emit_capacity, lane_id=lane,
            route_fn=_sharded_route_fn(axis, num_shards, lane,
                                       exchange_capacity, narrow),
            min_fn=lambda x: lax.pmin(x, axis),
            bulk_fn=bulk_fn, fault_fn=fault_fn,
            telem_fn=make_telem_fn(axis), wstart=wstart,
            sparse_lanes=resolve_sparse_lanes(cfg),
            census_fn=lambda x: lax.psum(x, axis),
            flow_fn=make_flow_fn(axis),
            sentinel_fn=make_sentinel_fn(axis),
        )
        out_sim, stats = _replicate_scalars(out_sim, local_sim, stats, axis)
        return out_sim, stats, next_min

    shmapped = _shard_map(
        _body, mesh=mesh, in_specs=(specs, P(), P()),
        out_specs=(specs, stats_specs, P()), check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0,) if donate else ())


def make_sharded_chunk(mesh: Mesh, axis: str, sim_template, cfg, step_fn,
                       *, end_time: int, wend_fn, chunk_windows: int,
                       exchange_capacity: int | None = None,
                       narrow: int | None = None, bulk_fn=None,
                       fault_fn=None, donate: bool = False):
    """make_sharded_window's chunked sibling: a jitted
    (sim, stats, wstart) -> (sim, stats, next_min) running up to
    `chunk_windows` full window rounds per dispatch under ONE
    shard_map (engine.make_chunk_body) — the per-window all-to-all,
    pmin barrier, fault rewrites, telemetry stores and sparse-census
    psum all stay on device between host barriers, so the host pays
    one dispatch per K windows.

    Stats accumulate in the carry: pass EngineStats.create() to get
    per-chunk deltas (what the supervisor's on_chunk consumes). Scalar
    replication (_replicate_scalars) runs once per chunk against the
    chunk's ENTRY state — correct because it psums deltas, and deltas
    over K windows compose. The window-end rule `wend_fn` comes from
    net.build.resolve_wend_fn (static min_jump or the adaptive live
    -table jump); rounds whose wstart passed end_time are no-ops, so a
    caller may keep one speculative chunk in flight past the end."""
    from shadow_tpu.core.engine import make_chunk_body

    num_shards, specs, stats_specs = _harness_specs(mesh, axis,
                                                    sim_template)

    def _body(local_sim, stats, wstart):
        lane = local_sim.net.lane_id
        chunk = make_chunk_body(
            step_fn, end_time=end_time, wend_fn=wend_fn,
            chunk_windows=chunk_windows,
            emit_capacity=cfg.emit_capacity,
            lane_fn=lambda s: s.net.lane_id,
            route_fn=_sharded_route_fn(axis, num_shards, lane,
                                       exchange_capacity, narrow),
            min_fn=lambda x: lax.pmin(x, axis),
            bulk_fn=bulk_fn, fault_fn=fault_fn,
            telem_fn=make_telem_fn(axis),
            sparse_lanes=resolve_sparse_lanes(cfg),
            census_fn=lambda x: lax.psum(x, axis),
            flow_fn=make_flow_fn(axis),
            sentinel_fn=make_sentinel_fn(axis),
        )
        out_sim, stats, next_min = chunk(local_sim, stats, wstart)
        out_sim, stats = _replicate_scalars(out_sim, local_sim, stats, axis)
        return out_sim, stats, next_min

    shmapped = _shard_map(
        _body, mesh=mesh, in_specs=(specs, stats_specs, P()),
        out_specs=(specs, stats_specs, P()), check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=(0,) if donate else ())


def make_sharded_runner(bundle, mesh: Mesh, axis: str = "hosts",
                        app_handlers=(), end_time: int | None = None,
                        exchange_capacity: int | None = None,
                        app_bulk=None, app_tcp_bulk=None,
                        tcp_bulk_lossless: bool = False,
                        fault_fn=None, warm_start: bool | None = None,
                        compile_info: dict | None = None):
    """Multi-chip variant of shadow_tpu.net.build.make_runner: a
    REUSABLE jitted sim -> (sim, stats) callable running the whole
    window loop under shard_map (benchmarks must reuse one callable —
    re-tracing the netstack costs seconds per call; see make_runner).
    The input sim may be unsharded; device_put inside applies the
    NamedShardings once per call."""
    from shadow_tpu.net.step import make_step_fn
    from shadow_tpu.net.build import (_resolve_caps, _resolve_fault_fn,
                                      _whole_run_key_fn, plan_times)

    caller_fault_fn = fault_fn
    # Capability trims are shard-invariant: the loss trim's counter
    # arithmetic and the omitted timer family are per-row, and the
    # guard's scalar trip counters take the generic delta-psum
    # (_replicate_scalars) like every other sticky latch.
    caps = _resolve_caps(bundle, caller_fault_fn)
    step = make_step_fn(bundle.cfg, app_handlers, caps=caps)
    bulk_fn = None
    if app_bulk is not None:
        from shadow_tpu.net.bulk import make_bulk_fn

        bulk_fn = make_bulk_fn(bundle.cfg, app_bulk, caps=caps)
    if bulk_fn is None and app_tcp_bulk is not None:
        # lane-local like the UDP pass (all its reads/writes are
        # per-row or replicated-table gathers), so it drops straight
        # into the shard-local window step
        from shadow_tpu.net.tcp_bulk import make_tcp_bulk_fn

        bulk_fn = make_tcp_bulk_fn(bundle.cfg, app_tcp_bulk,
                                   lossless=tcp_bulk_lossless, caps=caps)
    fault_fn = _resolve_fault_fn(bundle, fault_fn)
    end = end_time if end_time is not None else bundle.cfg.end_time
    return _make_whole_run(
        mesh, axis, bundle.sim, step,
        end_time=end,
        min_jump=bundle.min_jump,
        emit_capacity=bundle.cfg.emit_capacity,
        exchange_capacity=exchange_capacity,
        bulk_fn=bulk_fn, fault_fn=fault_fn,
        sparse_lanes=resolve_sparse_lanes(bundle.cfg),
        fault_times=plan_times(bundle),
        warm_key=_whole_run_key_fn(
            bundle, app_handlers, end=end, path="sharded_whole",
            chunk_windows=0, adaptive=False, fault_fn=caller_fault_fn,
            app_bulk=app_bulk, app_tcp_bulk=app_tcp_bulk,
            tcp_bulk_lossless=tcp_bulk_lossless,
            shards=mesh.shape[axis],
            exchange_capacity=exchange_capacity, caps=caps),
        warm_start=warm_start, compile_info=compile_info)


def run_sharded(bundle, mesh: Mesh, axis: str = "hosts", app_handlers=(),
                end_time: int | None = None,
                exchange_capacity: int | None = None,
                app_bulk=None, app_tcp_bulk=None,
                warm_start: bool | None = None,
                compile_info: dict | None = None):
    """One-shot multi-chip variant of shadow_tpu.net.build.run."""
    return make_sharded_runner(
        bundle, mesh, axis, app_handlers, end_time,
        exchange_capacity, app_bulk, app_tcp_bulk,
        warm_start=warm_start, compile_info=compile_info)(bundle.sim)
