from shadow_tpu.parallel.shard import (  # noqa: F401
    route_outbox_sharded,
    run_sharded,
    sharded_engine_run,
    sim_specs,
)
