"""Virtual processes: host-side Python coroutines against a
simulated-syscall surface.

This is the TPU-native replacement for the reference's L5 — the
interposition stack that loads real ELF binaries into linker
namespaces, interposes their libc calls, and runs them on cooperative
green threads (ref: process.c:1055-1195, interposer.c:37-170,
src/external/rpth). A TPU cannot dlmopen a Linux binary, so
applications are written as Python generator coroutines that *yield
syscalls* — the same contract as the ~400 process_emu_* entry points
(ref: process.h:103-437) with the same blocking semantics: a blocking
call suspends the coroutine (the rpth green-thread block,
pth_high.c) until the simulated kernel marks it runnable again
(the epoll notify -> process_continue chain, epoll.c:638-680,
process.c:1197-1275).

Scheduling granularity — an explicit deviation from the reference:
coroutines are resumed at conservative-window boundaries, not at
individual events. The device drains a whole window, the runtime
fetches readiness state once, and every runnable coroutine advances
until it blocks (the analog of `pth_yield` until all threads block,
process.c:1227-1229). Syscall effects are applied at the next window
start time. This batching is what makes host<->device traffic feasible
(SURVEY.md §7.4.4); latency-critical apps should be written as
on-device handler models instead (apps/pingpong, apps/bulk,
apps/phold).

Determinism: coroutines resume in host-id order, syscalls apply in
resume order, and window boundaries are deterministic — so runs are
exactly reproducible.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Generator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import simtime
from shadow_tpu.core.engine import EngineStats, step_window
from shadow_tpu.core.events import EmitBuffer, apply_emissions
from shadow_tpu.net import tcp as tcpmod
from shadow_tpu.net import udp as udpmod
from shadow_tpu.net.rings import gather_hs, set_hs
from shadow_tpu.net.sockets import sk_bind, sk_create
from shadow_tpu.net.state import NetConfig, SocketFlags, SocketType
from shadow_tpu.net.step import make_step_fn

I32 = jnp.int32
I64 = jnp.int64


# ---------------------------------------------------------------------
# syscall surface (the process_emu_* contract, ref: process.h:103-437)
# ---------------------------------------------------------------------

@dataclass(frozen=True)
class Sys:
    """One yielded syscall. Coroutines receive the result as the value
    of the `yield` expression."""

    op: str
    args: tuple = ()


def socket(stype=SocketType.UDP):
    return Sys("socket", (stype,))


def bind(fd, port):
    return Sys("bind", (fd, port))


def listen(fd):
    return Sys("listen", (fd,))


def connect(fd, ip, port):
    """TCP active open; blocks until ESTABLISHED (or reset -> -1)."""
    return Sys("connect", (fd, ip, port))


def accept(fd):
    """Blocks until a child is queued; returns the child fd."""
    return Sys("accept", (fd,))


def send(fd, nbytes):
    """TCP stream send; blocks until >0 bytes are accepted, returns
    that count (partial sends happen when the send buffer is near
    full)."""
    return Sys("send", (fd, nbytes))


def sendto(fd, ip, port, nbytes):
    """UDP datagram send; non-blocking, returns True if queued."""
    return Sys("sendto", (fd, ip, port, nbytes))


def recv(fd, maxbytes=1 << 30):
    """Blocks until data (returns byte count) or EOF (returns 0)."""
    return Sys("recv", (fd, maxbytes))


def recvfrom(fd):
    """UDP receive; blocks until a datagram arrives, returns
    (src_ip, src_port, nbytes)."""
    return Sys("recvfrom", (fd,))


def send_data(fd, data: bytes):
    """TCP stream send carrying REAL content (ref: the reference's
    plugins send actual buffers; payload bytes live host-side in the
    payload pool / stream store, payload.c:17-30). Blocks until >0
    bytes are accepted, returns that count; resend data[count:] for
    the remainder."""
    return Sys("send_data", (fd, data))


def recv_data(fd, maxbytes=1 << 30):
    """TCP stream receive returning actual bytes. Blocks until data
    (returns non-empty bytes) or EOF (returns b"")."""
    return Sys("recv_data", (fd, maxbytes))


def sendto_data(fd, ip, port, data: bytes):
    """UDP datagram send with real content: bytes go into the payload
    pool, the device packet carries the pool ref (W_PAYREF,
    packetfmt.py; mirrors Payload sharing, payload.c:17-30).
    Non-blocking, returns True if queued."""
    return Sys("sendto_data", (fd, ip, port, data))


def recvfrom_data(fd):
    """UDP receive with content; blocks until a datagram arrives,
    returns (src_ip, src_port, data). Datagrams sent without content
    (sendto) yield zero bytes of the advertised length."""
    return Sys("recvfrom_data", (fd,))


def close(fd):
    return Sys("close", (fd,))


SHUT_RD, SHUT_WR, SHUT_RDWR = 0, 1, 2


def shutdown(fd, how=SHUT_WR):
    """shutdown(2) analog (ref: process_emu_shutdown). SHUT_WR sends
    FIN behind any queued data while the fd stays readable — the
    half-close the reference's shutdown/ test exercises. SHUT_RD is a
    local no-op (arriving data is still buffered, like Linux);
    SHUT_RDWR behaves as SHUT_WR."""
    return Sys("shutdown", (fd, how))


def sleep(ns):
    """nanosleep (ref: process_emu_nanosleep -> pth_nanosleep,
    process.c:3141-3148); wakes at the first window boundary >= the
    deadline."""
    return Sys("sleep", (ns,))


def gettime():
    """gettimeofday/clock_gettime analog: the current sim time in ns
    (ref: worker_getEmulatedTime, worker.c:385-390)."""
    return Sys("gettime", ())


def gethostbyname(name: str):
    """Runtime name resolution through the simulation's DNS registry
    (ref: process_emu_gethostbyname family, process.h:237-250, backed
    by dns_resolveNameToAddress, dns.c). Returns the host's network IP
    as an int, or -1 when the name is not registered — so configs can
    address peers by hostname instead of IP hint, exactly as reference
    plugins do."""
    return Sys("gethostbyname", (name,))


def getaddrinfo(name: str):
    """Alias of gethostbyname for the modern-API spelling the
    reference also interposes (process_emu_getaddrinfo)."""
    return Sys("gethostbyname", (name,))


TIMER_FD_BASE = 1 << 19   # timerfd handles above the pipe space


def timerfd_create():
    """timerfd_create() analog: allocates one of the host's
    cfg.timers_per_host timer slots (ref: timer.c / host_createDescriptor
    DT_TIMER). Returns a timer fd, or -1 when slots are exhausted."""
    return Sys("timerfd_create", ())


def timerfd_settime(tfd, expire_ns, interval_ns=0):
    """Arm to fire at ABSOLUTE sim time expire_ns, then every
    interval_ns (0 = one-shot); expire_ns 0 disarms (ref:
    timer_setTime, timer.c:201-...)."""
    return Sys("timerfd_settime", (tfd, expire_ns, interval_ns))


def timerfd_read(tfd):
    """Blocking timerfd read: waits until >=1 expiration, returns the
    expiration count since the last read (ref: timer read semantics,
    timer.c)."""
    return Sys("timerfd_read", (tfd,))


class SO:
    """setsockopt/getsockopt option names (the SOL_SOCKET subset the
    reference's sockbuf test exercises, test_sockbuf.c:57-88)."""

    SNDBUF = 7   # Linux SO_SNDBUF
    RCVBUF = 8   # Linux SO_RCVBUF


def setsockopt(fd, opt, value):
    """Set SO_SNDBUF/SO_RCVBUF. Like the reference, pinning a buffer
    size disables that direction's TCP autotuning (the user-override
    rule, master.c:355-364 / tcp.c:407-592)."""
    return Sys("setsockopt", (fd, opt, value))


def getsockopt(fd, opt):
    return Sys("getsockopt", (fd, opt))


def ioctl_inq(fd):
    """ioctl(FIONREAD/SIOCINQ): bytes available to read (TCP: in-order
    stream bytes awaiting recv; UDP: buffered datagram bytes)."""
    return Sys("ioctl_inq", (fd,))


def ioctl_outq(fd):
    """ioctl(SIOCOUTQ/TIOCOUTQ): unsent+unacked output bytes (TCP) or
    queued datagram bytes (UDP)."""
    return Sys("ioctl_outq", (fd,))


def wait_readable(fds):
    """Convenience: blocks until one of `fds` is readable, returns the
    list of readable fds (a level-triggered EPOLLIN wait without an
    explicit epoll object)."""
    return Sys("wait_readable", (tuple(fds),))


def poll_fds(fds, timeout_ns: int = -1):
    """poll(2) (ref: host_poll, host.c:949-1009): fds is a sequence of
    (fd, events) with events an EPOLL.IN|OUT mask (POLLIN/POLLOUT).
    Returns [(fd, revents), ...] for ready fds — empty list on
    timeout. timeout_ns < 0 blocks until ready; 0 polls without
    blocking (may return [])."""
    return Sys("poll", (tuple(tuple(x) for x in fds), int(timeout_ns)))


def select_fds(rfds, wfds, timeout_ns: int = -1):
    """select(2) (ref: host_select, host.c:852-947): returns
    (readable, writable) fd lists; ([], []) on timeout. Same timeout
    semantics as poll_fds."""
    return Sys("select", (tuple(rfds), tuple(wfds), int(timeout_ns)))


# ---------------------------------------------------------------------
# epoll: the readiness engine (ref: descriptor/epoll.c)
# ---------------------------------------------------------------------
#
# The reference's epoll is the app-wakeup spine: descriptor status
# changes notify EpollWatches, which schedule a task that re-enters
# process_continue (epoll.c:583-680). Here the *status* half lives on
# device (SocketFlags.READABLE/WRITABLE maintained by the netstack —
# udp_deliver/udp_recv, tcp data/ACK paths, sk_enqueue_out, NIC drain)
# and the *watch* half is host-side per-process state polled at
# window-boundary resumption. Level/edge/oneshot flag algebra follows
# epoll.c:24-67; an epoll is itself watchable (nesting, epoll.c:96-98)
# — its readiness is "has at least one ready watch".
#
# Edge-trigger granularity — an explicit deviation: edges are detected
# between consecutive polls of the same watch (readiness transitions
# within one conservative window collapse), consistent with the
# window-batched scheduling model described in the module docstring.

class EPOLL:
    IN = 1        # maps to SocketFlags.READABLE
    OUT = 2       # maps to SocketFlags.WRITABLE
    ET = 4        # edge-triggered
    ONESHOT = 8   # disarm after first report (re-arm via MOD)
    CTL_ADD = 1
    CTL_MOD = 2
    CTL_DEL = 3


EPOLL_FD_BASE = 1 << 16   # epoll fds live above the socket-slot space
PIPE_FD_BASE = 1 << 17    # pipe/socketpair fds above the epoll space
FILE_FD_BASE = 1 << 18    # virtual-filesystem fds above the pipe space


# ---------------------------------------------------------------------
# r5 surface breadth (VERDICT r4 #4): files, random, signals, threads
# (ref: process.h:103-437 — the process_emu_{open,read,write,rand,
# kill,sigaction,...} families, and rpth's pthread layer,
# src/external/rpth/pthread.c)
# ---------------------------------------------------------------------

# signal numbers the reference tests exercise (src/test/signal,
# src/test/unistd)
SIGUSR1 = 10
SIGSEGV = 11
SIGUSR2 = 12

SEEK_SET, SEEK_CUR, SEEK_END = 0, 1, 2


def fopen(path: str, mode: str = "r"):
    """open/fopen analog on the host's virtual filesystem (ref:
    process_emu_open/fopen; the reference redirects relative paths
    into the host's data directory, process.c). Returns fd or -1
    (ENOENT for "r" on a missing file). Files are per-HOST like
    channels (the fork-inherited-descriptor analog)."""
    return Sys("fopen", (path, mode))


def funlink(path: str):
    """unlink(2) analog; returns 0 or -1 (ENOENT)."""
    return Sys("funlink", (path,))


def fseek(fd, off: int, whence: int = SEEK_SET):
    """lseek(2) analog; returns the new offset or -1."""
    return Sys("fseek", (fd, off, whence))


def fstat_size(fd):
    """fstat(2) st_size; returns the size or -1 (EBADF)."""
    return Sys("fstat_size", (fd,))


def getrandom(n: int):
    """getrandom(2) / read of /dev/urandom: n bytes from the host's
    deterministic random source (ref: the reference seeds each host's
    random from the master seed hierarchy, host.c random; two runs of
    one seed return identical streams)."""
    return Sys("getrandom", (n,))


def c_rand():
    """rand(3) analog from the same per-host source: [0, 2**31)."""
    return Sys("c_rand", ())


def getpid():
    """Returns the virtual pid (spawn order, 1-based — the reference
    hands plugins their per-process id the same way)."""
    return Sys("getpid", ())


def gethostname():
    """Returns the host's configured name (ref:
    process_emu_gethostname reads the Host's name, process.c)."""
    return Sys("gethostname", ())


def sigaction(sig: int, handler):
    """Install `handler(signum)` for sig (ref: process_emu_sigaction;
    handlers run host-side at delivery, the pth-dispatched handler
    analog). Returns 0."""
    return Sys("sigaction", (sig, handler))


def raise_sig(sig: int):
    """raise(3): deliver sig to the calling process — the installed
    handler runs before this returns. An unhandled signal kills the
    process (the plugin-error path, slave.c:468-473). Returns 0 if
    handled."""
    return Sys("raise_sig", (sig,))


def kill(pid: int, sig: int):
    """kill(2) to a virtual pid on the SAME host (ref:
    process_emu_kill; cross-host signals don't exist). Returns 0, or
    -1 (ESRCH) for an unknown/foreign pid."""
    return Sys("kill", (pid, sig))


def thread_create(fn):
    """pthread_create analog: start `fn(host)` — a generator yielding
    vproc syscalls — as another coroutine of the SAME process context
    (shared host fds/channels/files; ref: rpth pthread_create spawns
    a green thread in the process's pth scheduler). Returns its tid."""
    return Sys("thread_create", (fn,))


def thread_join(tid: int):
    """pthread_join analog: blocks until the thread's coroutine
    completes; returns its StopIteration value (or None)."""
    return Sys("thread_join", (tid,))


def mutex_init():
    """pthread_mutex_init analog (host-scoped like fds); returns a
    mutex id."""
    return Sys("mutex_init", ())


def mutex_lock(mid: int):
    """Blocks until acquired (ref: rpth pth_mutex_acquire — green
    threads interleave only at yield points, so the lock serializes
    critical sections across this host's coroutines)."""
    return Sys("mutex_lock", (mid,))


def mutex_trylock(mid: int):
    """Returns True if acquired, False if held (EBUSY)."""
    return Sys("mutex_trylock", (mid,))


def mutex_unlock(mid: int):
    return Sys("mutex_unlock", (mid,))


def cond_init():
    """pthread_cond_init analog (host-scoped like mutexes); returns a
    condition id (ref: rpth pth_cond_init, src/external/rpth
    pthread.c cond family)."""
    return Sys("cond_init", ())


def cond_wait(cid: int, mid: int):
    """pthread_cond_wait analog with rpth semantics (rpth pthread.c:
    pthread_cond_wait -> pth_cond_await with the bound mutex):
    atomically releases the HELD mutex `mid`, blocks until signaled,
    then re-acquires the mutex before returning 0. Calling without
    owning the mutex returns -1 (EPERM)."""
    return Sys("cond_wait", (cid, mid))


def cond_signal(cid: int):
    """Wake the oldest waiter (FIFO, the deterministic analog of
    pth_cond_notify's single-wake); a signal with no waiters is lost,
    like the real thing. Returns 0."""
    return Sys("cond_signal", (cid,))


def cond_broadcast(cid: int):
    """Wake ALL current waiters (pth_cond_notify broadcast=TRUE).
    Each re-acquires the mutex in turn. Returns 0."""
    return Sys("cond_broadcast", (cid,))


# errno values the emulated surface reports (the subset the
# reference's process_emu_* stubs return, process.h:103-437 — calls
# whose mechanism shadow cannot virtualize set ENOSYS and return -1
# via the process_undefined.h stub path)
ENOENT = 2
ESRCH = 3
ECHILD = 10
EAGAIN = 11
ENOSYS = 38


def fork():
    """fork(2): the reference cannot fork a plugin (a forked child
    would escape the simulation — the interposed call warns and
    returns -1/ENOSYS, the process_undefined stub behavior). Returns
    -1; get_errno() reports ENOSYS."""
    return Sys("fork", ())


def execv(path: str, argv=()):
    """execve(2) family: same unsupported-call contract as fork —
    returns -1/ENOSYS instead of raising (a real exec would replace
    the worker process image)."""
    return Sys("exec", (path, tuple(argv)))


def system(cmd: str):
    """system(3) is fork+exec+wait; unsupported the same way. Returns
    -1; get_errno() reports ENOSYS."""
    return Sys("system", (cmd,))


def get_errno():
    """The calling process's last emulated errno (the
    __errno_location analog the reference resolves per plugin,
    process.c:88-106); 0 when no failed call has set one."""
    return Sys("errno", ())


def pipe():
    """Unidirectional intra-host byte conduit; returns (rfd, wfd)
    (ref: Channel, channel.c:22-60 — two linked descriptors over a
    ByteQueue). Fds are per-HOST: another process on the same host may
    use them (the fork-inherited-descriptor analog)."""
    return Sys("pipe", ())


def socketpair():
    """Bidirectional intra-host conduit; returns (fd1, fd2) — two
    cross-linked channels (ref: channel_new CT_NONE pair +
    channel_setLinkedChannel, channel.c:147-180)."""
    return Sys("socketpair", ())


def write(fd, data: bytes):
    """Write bytes to a pipe/socketpair fd; blocks while the channel
    buffer is full, returns the count accepted (partial writes
    happen); returns -1 when the read side is closed (EPIPE)."""
    return Sys("write", (fd, data))


def read(fd, maxbytes=1 << 30):
    """Read from a pipe/socketpair fd; blocks until data (returns
    bytes) or writer-closed EOF (returns b"")."""
    return Sys("read", (fd, maxbytes))


def epoll_create():
    """Returns an epoll fd (ref: epoll_new, epoll.c)."""
    return Sys("epoll_create", ())


def epoll_ctl(epfd, op, fd, events=0):
    """op in {EPOLL.CTL_ADD, CTL_MOD, CTL_DEL}; events is a mask of
    EPOLL.IN|OUT plus EPOLL.ET/ONESHOT behavior flags
    (ref: epoll_control, epoll.c)."""
    return Sys("epoll_ctl", (epfd, op, fd, events))


def epoll_wait(epfd):
    """Blocks until at least one watch reports; returns a list of
    (fd, ready_mask) pairs (ref: epoll_getEvents + the notify ->
    process_continue chain, epoll.c:344-366,638-680)."""
    return Sys("epoll_wait", (epfd,))


@dataclass
class _EpollWatch:
    interest: int         # EPOLL.IN|OUT
    flags: int            # EPOLL.ET|ONESHOT
    # Edge bases: the readiness generations consumed by the previous
    # poll. -1 = never polled, so readiness present at ADD time is
    # reported once (Linux's ep_insert queues an initial event for a
    # ready fd). New arrivals bump the device-side generation, so an
    # already-readable socket still edges on each arrival.
    prev_in_gen: int = -1
    prev_out_gen: int = -1
    armed: bool = True    # oneshot disarm state


@dataclass
class _Epoll:
    watches: "dict[int, _EpollWatch]" = field(default_factory=dict)


# ---------------------------------------------------------------------
# channels: pipe / socketpair (ref: descriptor/channel.c)
# ---------------------------------------------------------------------

CHANNEL_CAP = 65536   # per-direction buffer limit (ref: the ByteQueue
                      # capacity channels enforce, channel.c:22-60)


@dataclass
class _ByteQ:
    """One direction of a channel — the ByteQueue the two linked
    descriptors share (ref: channel.c:22-60). Host-side only: channel
    traffic never touches the simulated network, matching the
    reference where Channel bypasses the NIC entirely."""
    buf: bytearray = field(default_factory=bytearray)
    cap: int = CHANNEL_CAP
    writers: int = 1
    readers: int = 1
    in_gen: int = 0    # bumped on write/writer-close (readability edge)
    out_gen: int = 0   # bumped on read/reader-close (writability edge)


@dataclass
class _ChanEnd:
    """What one pipe/socketpair fd can do: read from recv_q, write to
    send_q (pipe ends have one of the two, socketpair ends both)."""
    recv_q: "Optional[_ByteQ]" = None
    send_q: "Optional[_ByteQ]" = None


# ---------------------------------------------------------------------
# shared op table: backend-independent host-side kernel state
# ---------------------------------------------------------------------
#
# These syscalls never touch the device OR the real kernel — files,
# the deterministic random source, pids, hostnames, signals, and the
# unsupported-call stubs. The simulation backend (ProcessRuntime) and
# the real-host-kernel backend (hostrun.executor.HostKernelExecutor)
# dispatch them through ONE table, so the two backends cannot drift on
# this surface — the conformance subsystem (docs/7-conformance.md)
# then only has to validate the ops that genuinely differ per backend.


@dataclass
class HostSideState:
    """Per-run state behind the shared ops (the host-side half of the
    reference's Host: data-dir files, the per-host Random, per-process
    stdout/stderr — host.c / process.c)."""

    seed: int
    host_names: list
    data_dir: Optional[str] = None
    fs: dict = field(default_factory=dict)          # (host, path) -> bytearray
    file_fds: dict = field(default_factory=dict)    # (host, fd) -> cursor
    next_file_fd: dict = field(default_factory=dict)
    rand: dict = field(default_factory=dict)        # host -> np Generator
    stdio: dict = field(default_factory=dict)       # (host, pid, fd)


def host_rand(st: HostSideState, h: int) -> "np.random.Generator":
    """The host's deterministic random source (ref: each Host gets
    its own Random seeded from the master seed, host.c) — derived
    from (seed, host), so runs of one seed are bit-identical, hosts
    are independent, and BOTH backends draw the same stream."""
    g = st.rand.get(h)
    if g is None:
        g = np.random.default_rng(
            np.random.SeedSequence([int(st.seed), 0x5EED, h]))
        st.rand[h] = g
    return g


def file_open(st: HostSideState, h: int, path: str, mode: str) -> int:
    exists = (h, path) in st.fs
    if mode.startswith("r") and not exists:
        return -1                 # ENOENT ("r" and "r+" both
                                  # require the file to exist)
    if mode in ("w", "w+") or not exists:
        st.fs[(h, path)] = bytearray()
    fd = st.next_file_fd.get(h, FILE_FD_BASE)
    st.next_file_fd[h] = fd + 1
    st.file_fds[(h, fd)] = {
        "path": path, "pos": 0,
        "rd": mode in ("r", "r+", "w+", "a+"),
        "wr": mode not in ("r",)}
    if mode in ("a", "a+"):
        st.file_fds[(h, fd)]["pos"] = len(st.fs[(h, path)])
    return fd


def file_write(st: HostSideState, h: int, fd: int, data: bytes) -> int:
    ent = st.file_fds.get((h, fd))
    if ent is None or not ent["wr"]:
        return -1                      # EBADF
    buf = st.fs.setdefault((h, ent["path"]), bytearray())
    pos = ent["pos"]
    if pos > len(buf):
        buf.extend(b"\0" * (pos - len(buf)))
    buf[pos:pos + len(data)] = data
    ent["pos"] = pos + len(data)
    return len(data)


def file_read(st: HostSideState, h: int, fd: int, maxb: int):
    ent = st.file_fds.get((h, fd))
    if ent is None or not ent["rd"]:
        return -1                      # EBADF
    buf = st.fs.get((h, ent["path"]), b"")
    pos = ent["pos"]
    out = bytes(buf[pos:pos + maxb])
    ent["pos"] = pos + len(out)
    return out


def stdio_write(st: HostSideState, host_name: str, host: int, pid: int,
                fd: int, data: bytes) -> int:
    """Per-process stdout/stderr (ref: process.c's per-process
    <data>/hosts/<name>/*.stdout|stderr files): buffered in memory,
    appended to real files when data_dir is set."""
    key = (host, pid, fd)
    st.stdio.setdefault(key, bytearray()).extend(data)
    if st.data_dir is not None:
        import os

        d = os.path.join(st.data_dir, "hosts", host_name)
        os.makedirs(d, exist_ok=True)
        suffix = "stdout" if fd == 1 else "stderr"
        with open(os.path.join(d, f"proc{pid}.{suffix}"), "ab") as f:
            f.write(data)
    return len(data)


def _op_fopen(st, rt, p, a):
    return True, file_open(st, p.host, a[0], a[1])


def _op_funlink(st, rt, p, a):
    if st.fs.pop((p.host, a[0]), None) is not None:
        return True, 0
    p.last_errno = ENOENT
    return True, -1


def _op_fseek(st, rt, p, a):
    ent = st.file_fds.get((p.host, a[0]))
    if ent is None:
        return True, -1           # EBADF
    off, whence = a[1], a[2]
    size = len(st.fs.get((p.host, ent["path"]), b""))
    base = (0 if whence == SEEK_SET
            else ent["pos"] if whence == SEEK_CUR else size)
    if base + off < 0:
        return True, -1           # EINVAL
    ent["pos"] = base + off
    return True, ent["pos"]


def _op_fstat_size(st, rt, p, a):
    ent = st.file_fds.get((p.host, a[0]))
    if ent is None:
        return True, -1
    return True, len(st.fs.get((p.host, ent["path"]), b""))


def _op_getrandom(st, rt, p, a):
    return True, host_rand(st, p.host).bytes(a[0])


def _op_c_rand(st, rt, p, a):
    return True, int(host_rand(st, p.host).integers(0, 1 << 31))


def _op_getpid(st, rt, p, a):
    return True, p.pid


def _op_gethostname(st, rt, p, a):
    return True, st.host_names[p.host]


def _op_sigaction(st, rt, p, a):
    p.sig_handlers[a[0]] = a[1]
    return True, 0


def _op_raise_sig(st, rt, p, a):
    return True, rt._deliver_signal(p, a[0])


def _op_kill(st, rt, p, a):
    pid, sig = a
    tgt = next((q for q in rt.procs
                if q.pid == pid and q.host == p.host and not q.done),
               None)
    if tgt is None:
        p.last_errno = ESRCH
        return True, -1           # ESRCH
    return True, rt._deliver_signal(tgt, sig)


def _op_unsupported(st, rt, p, a):
    """fork/exec/system: the reference interposes these and fails
    them with ENOSYS rather than letting a plugin escape the
    simulation (the process_undefined.h stub contract,
    process.h:103-437) — return the errno instead of raising."""
    p.last_errno = ENOSYS
    return True, -1


def _op_errno(st, rt, p, a):
    return True, p.last_errno


# the shared table: op -> fn(state, runtime, proc, args). `runtime`
# is duck-typed (.procs, ._deliver_signal) so both backends qualify.
SHARED_OPS = {
    "fopen": _op_fopen,
    "funlink": _op_funlink,
    "fseek": _op_fseek,
    "fstat_size": _op_fstat_size,
    "getrandom": _op_getrandom,
    "c_rand": _op_c_rand,
    "getpid": _op_getpid,
    "gethostname": _op_gethostname,
    "sigaction": _op_sigaction,
    "raise_sig": _op_raise_sig,
    "kill": _op_kill,
    "fork": _op_unsupported,
    "exec": _op_unsupported,
    "system": _op_unsupported,
    "errno": _op_errno,
}


# ---------------------------------------------------------------------
# runtime
# ---------------------------------------------------------------------

ProcFn = Callable[..., Generator]  # called as proc_fn(host_id) -> generator


@dataclass
class _Proc:
    host: int
    gen: Generator
    start_time: int = 0
    stop_time: int = -1            # -1 = run until completion
    started: bool = False
    done: bool = False
    # blocking state
    block: Optional[Sys] = None
    pending: Optional[Sys] = None  # next syscall to execute
    wake_time: int = -1            # for sleep
    # per-process epoll instances (epfd -> _Epoll)
    epolls: "dict[int, _Epoll]" = field(default_factory=dict)
    next_epfd: int = EPOLL_FD_BASE
    # r5 surface breadth: virtual pid, installed signal handlers,
    # and the generator's return value (pthread_join's result)
    pid: int = 0
    sig_handlers: dict = field(default_factory=dict)
    result: object = None
    # last failing syscall's errno (the process_emu errno cell,
    # process.h; read back via get_errno())
    last_errno: int = 0


class ProcessRuntime:
    """Runs virtual processes over a SimBundle (the master/slave loop
    of the reference, slave.c:413-466, with coroutine continuation in
    place of pth scheduling)."""

    def __init__(self, bundle, app_handlers=(), mesh=None, axis="hosts"):
        """`mesh`: optional jax.sharding.Mesh — the window loop then
        runs under shard_map with the all-to-all exchange + pmin
        barrier (parallel/shard.py), hosts sharded over `axis`.
        Syscall application stays host-driven; its array updates
        operate on the sharded state transparently."""
        self.bundle = bundle
        self.cfg: NetConfig = bundle.cfg
        self.sim = bundle.sim
        self.procs: list[_Proc] = []
        # per-host timerfd slot allocator (timerfd_create) and
        # per-(host,slot) read counter (keeps the ET edge base
        # monotone: tm_expirations resets on read, so fires alone
        # would repeat old values)
        self._timer_alloc: dict = {}
        self._timer_reads: dict = {}
        self._step = make_step_fn(self.cfg, app_handlers)
        if mesh is not None:
            from shadow_tpu.parallel.shard import make_sharded_window

            self._jit_window = make_sharded_window(
                mesh, axis, bundle.sim, self.cfg, self._step)
        else:
            self._jit_window = jax.jit(self._window)
        # host-side snapshots of sk_flags / tcp.st, fetched at most
        # once between state mutations (readiness polls and blocked-
        # syscall retries would otherwise do one device->host transfer
        # per process per window)
        self._flags_cache = None
        self._tcp_st_cache = None
        # --- payload content (ref: payload.c) -------------------------
        # UDP datagram bytes live in the refcounted pool; the device
        # packet carries the pool id (W_PAYREF). TCP stream bytes live
        # in per-direction FIFOs keyed by (srcHost, srcPort, dstHost,
        # dstPort) — the device models timing/windows/retransmission
        # and tells us how many in-order bytes each recv delivered, so
        # content follows by popping that many bytes off the FIFO.
        from shadow_tpu.native.pool import PayloadPool
        self.pool = PayloadPool()
        self._streams: dict[tuple, bytearray] = {}
        # channels (pipe/socketpair) are per-HOST like the device
        # socket table: keyed (host, fd) so same-host processes share
        # them (the fork-inherited-descriptor analog, channel.c)
        self._channels: dict[tuple, _ChanEnd] = {}
        self._next_pipe_fd: dict[int, int] = {}
        # r5 surface breadth (VERDICT r4 #4) ---------------------------
        # backend-independent host-side kernel state (virtual
        # filesystem, deterministic per-host random, per-process
        # stdio) lives in HostSideState so the SHARED_OPS table can
        # serve both this runtime and hostrun's real-kernel executor;
        # the _fs/_file_fds/... names alias into it for compat
        self.host_state = HostSideState(
            seed=int(self.cfg.seed), host_names=list(bundle.host_names))
        self._fs = self.host_state.fs                  # (host, path)
        self._file_fds = self.host_state.file_fds      # (host, fd)
        self._next_file_fd = self.host_state.next_file_fd
        self._rand = self.host_state.rand
        self._stdio = self.host_state.stdio            # (host,pid,fd)
        # pids, host mutexes + condition variables
        self._next_pid = 1
        self._mutexes: dict[tuple, int] = {}           # (host,mid)->pid|0
        self._next_mutex: dict[int, int] = {}
        # cond vars (rpth pthread.c): (host,cid) -> OrderedDict of
        # pid -> signaled flag, insertion order = FIFO wakeup order
        self._conds: dict[tuple, dict] = {}
        self._next_cond: dict[int, int] = {}
        # set by _exec when a syscall unblocks OTHER processes without
        # itself being in chan_ops (cond_wait's mutex release);
        # _resume_all folds it into chan_activity
        self._chan_kick = False
        # optional TraceRecorder (hostrun.trace): when set, every
        # completed syscall + process exit is recorded for the
        # dual-mode differential checker (docs/7-conformance.md)
        self.trace = None
        # host-side copy of the (static) IP tables for addr -> host id
        self._ip_sorted = np.asarray(self.sim.net.ip_sorted)
        self._host_of_ip_sorted = np.asarray(self.sim.net.host_of_ip_sorted)
        # dispatch accounting (SURVEY §7.4.4 batching evidence): one
        # "dispatch" = one fused device op (_apply); one "syscall" =
        # one coroutine request executed. Batched, dispatches grow
        # ~per-window-per-op-kind, not per syscall.
        self.stat_device_dispatches = 0
        self.stat_syscalls = 0

    @property
    def data_dir(self):
        """Host data directory for per-process stdout/stderr files
        (ref: process.c maintains <data>/hosts/<name>/*.stdout);
        None = keep in memory only (stdio_of reads either way)."""
        return self.host_state.data_dir

    @data_dir.setter
    def data_dir(self, value):
        self.host_state.data_dir = value

    # -- process registration -----------------------------------------

    def spawn(self, host: int, proc_fn: ProcFn, start_time: int = 0,
              stop_time: int = -1):
        """Register proc_fn(host) to start at sim time start_time
        (ref: <process starttime>, configuration.h:96-101). A
        non-negative stop_time kills the coroutine at that sim time
        (GeneratorExit at its blocked yield — the analog of
        process_stop aborting the plugin main thread,
        process.c:1286-1324; use try/finally in the coroutine for
        cleanup)."""
        gen = proc_fn(host)
        # fail loudly here, not as an opaque AttributeError deep in the
        # window loop: the contract is a generator yielding syscalls
        if not hasattr(gen, "send") or not hasattr(gen, "close"):
            raise TypeError(
                f"virtual process for host {host} returned "
                f"{type(gen).__name__}, not a generator (its main/"
                f"proc_fn must be or return a generator yielding vproc "
                f"syscalls)")
        self.procs.append(_Proc(host=host, gen=gen,
                                start_time=start_time,
                                stop_time=stop_time,
                                pid=self._next_pid))
        self._next_pid += 1

    # -- device side ----------------------------------------------------

    def _window(self, sim, wstart, wend):
        stats = EngineStats.create()
        sim, stats, next_min = step_window(
            sim, stats, self._step, wend,
            emit_capacity=self.cfg.emit_capacity,
            lane_id=sim.net.lane_id,
        )
        return sim, stats, next_min

    # -- syscall execution ---------------------------------------------

    def _lane(self, host):
        m = np.zeros(self.cfg.num_hosts, bool)
        m[host] = True
        return jnp.asarray(m)

    def _apply(self, fn, now=0):
        """Run a state-op that may emit events, then fold the emissions
        into the queues exactly like a device micro-step does. Any
        nic_send_now bits the op set are converted into NIC_SEND
        events — no pipeline send drain runs out here."""
        from shadow_tpu.net import nic

        self.stat_device_dispatches += 1
        buf = EmitBuffer.create(self.cfg.num_hosts, self.cfg.emit_capacity,
                                nwords=self.cfg.words_width)
        sim, buf = fn(self.sim, buf)
        sim, buf = nic.flush_wants_send(sim, buf, now)
        q, out = apply_emissions(sim.events, sim.outbox, buf,
                                 sim.net.lane_id)
        self.sim = sim.replace(events=q, outbox=out)
        self._flags_cache = None
        self._tcp_st_cache = None

    # -- payload content helpers ----------------------------------------

    def _host_of(self, ip: int, default: int) -> int:
        """Map an IP to its host index host-side (the np mirror of
        net.host_of_ip); loopback / unknown falls back to `default`
        (the caller's own host)."""
        if (ip >> 24) == 127:
            return default
        i = int(np.searchsorted(self._ip_sorted, ip))
        if i < len(self._ip_sorted) and int(self._ip_sorted[i]) == ip:
            return int(self._host_of_ip_sorted[i])
        return default

    def _stream_key(self, p: _Proc, fd: int, sending: bool) -> tuple:
        """Direction key of the TCP content FIFO for (p.host, fd)."""
        net = self.sim.net
        h = p.host
        my_port = int(net.sk_bound_port[h, fd])
        peer_ip = int(net.sk_peer_ip[h, fd])
        peer_port = int(net.sk_peer_port[h, fd])
        peer_h = self._host_of(peer_ip, default=h)
        if sending:
            return (h, my_port, peer_h, peer_port)
        return (peer_h, peer_port, h, my_port)

    # -- readiness (the epoll.c status engine, host side) ---------------

    def _net_rows(self):
        if self._flags_cache is None:
            net = self.sim.net
            self._flags_cache = (
                np.asarray(net.sk_flags),
                np.asarray(net.sk_in_gen),
                np.asarray(net.sk_out_gen),
            )
        return self._flags_cache

    def _flags_row(self, host):
        return self._net_rows()[0][host]

    def _tcp_st(self, host, fd) -> int:
        """TCP state read through the per-window host-side cache (one
        device fetch per invalidation instead of one per blocked
        connect per window)."""
        if self._tcp_st_cache is None:
            self._tcp_st_cache = np.asarray(self.sim.tcp.st)
        return int(self._tcp_st_cache[host, fd])

    def _sk_flag(self, host, fd, bit) -> bool:
        return bool(int(self._flags_row(host)[fd]) & bit)

    def _fd_gens(self, p: _Proc, fd: int, _depth: int = 0):
        """(in_gen, out_gen) of a socket fd; for a nested epoll, the
        sum of its watches' generations (monotonic — any child edge
        advances the parent's)."""
        if fd >= TIMER_FD_BASE:
            # monotone edge base: pending fires + 2x completed reads
            # (a read consumes at least one fire, so the sum never
            # revisits a previous value) + re-arms
            ts = fd - TIMER_FD_BASE
            n = int(self.sim.net.tm_expirations[p.host, ts])
            g = int(self.sim.net.tm_gen[p.host, ts])
            r = self._timer_reads.get((p.host, ts), 0)
            return (n + 2 * r + g, 0)
        if fd >= PIPE_FD_BASE:
            ep = self._channels.get((p.host, fd))
            if ep is None:
                return (0, 0)
            return (ep.recv_q.in_gen if ep.recv_q else 0,
                    ep.send_q.out_gen if ep.send_q else 0)
        if fd >= EPOLL_FD_BASE:
            ep = p.epolls.get(fd)
            if ep is None or _depth > 8:
                return (0, 0)
            gi = go = 0
            for wfd in ep.watches:
                a, b = self._fd_gens(p, wfd, _depth + 1)
                gi += a
                go += b
            return (gi, go)
        _, ig, og = self._net_rows()
        return (int(ig[p.host][fd]), int(og[p.host][fd]))

    def _watch_report(self, p: _Proc, wfd: int, w: _EpollWatch,
                      _depth: int = 0) -> int:
        """What this watch would report NOW (non-destructive)."""
        cur = self._fd_ready(p, wfd, _depth) & w.interest
        if not (w.flags & EPOLL.ET):
            return cur
        gin, gout = self._fd_gens(p, wfd, _depth)
        report = 0
        if (cur & EPOLL.IN) and gin != w.prev_in_gen:
            report |= EPOLL.IN
        if (cur & EPOLL.OUT) and gout != w.prev_out_gen:
            report |= EPOLL.OUT
        return report

    def _fd_ready(self, p: _Proc, fd: int, _depth: int = 0) -> int:
        """Current EPOLL.IN|OUT readiness of a socket fd, pipe fd, or
        a nested epoll fd (an epoll is readable when it would report
        at least one event — epoll-as-descriptor, ref: epoll.c:96-98)."""
        if fd >= TIMER_FD_BASE:
            # a timerfd is readable while unread expirations exist
            # (ref: timer readiness drives epoll, timer.c + epoll.c)
            ts = fd - TIMER_FD_BASE
            n = int(self.sim.net.tm_expirations[p.host, ts])
            return EPOLL.IN if n > 0 else 0
        if fd >= PIPE_FD_BASE:
            # channel status bits (ref: channel.c:22-60,147-180 flips)
            ep = self._channels.get((p.host, fd))
            if ep is None:
                return 0
            m = 0
            if ep.recv_q and (ep.recv_q.buf or ep.recv_q.writers == 0):
                m |= EPOLL.IN
            if ep.send_q and (len(ep.send_q.buf) < ep.send_q.cap
                              or ep.send_q.readers == 0):
                m |= EPOLL.OUT
            return m
        if fd >= EPOLL_FD_BASE:
            if _depth > 8:       # nesting depth guard (cycles)
                return 0
            ep = p.epolls.get(fd)
            if ep is None:
                return 0
            for wfd, w in ep.watches.items():
                if w.armed and self._watch_report(p, wfd, w, _depth + 1):
                    return EPOLL.IN
            return 0
        flags = int(self._flags_row(p.host)[fd])
        m = 0
        if flags & SocketFlags.READABLE:
            m |= EPOLL.IN
        if flags & SocketFlags.WRITABLE:
            m |= EPOLL.OUT
        return m

    def _exec(self, p: _Proc, call: Sys, now: int):
        """Execute one non-blocking syscall (or the completion of a
        blocking one). Blocking decisions come from the live device
        state / the op's own result — never from a snapshot, which
        would go stale the moment an earlier syscall in the same pass
        mutated state. Returns (ready, result).

        Ops in BATCH_OPS have exactly ONE implementation — the batched
        one; a lone call is a singleton batch (no second copy of the
        semantics to drift)."""
        if call.op in self.BATCH_OPS:
            # _exec_batch reads each proc's pending call (p.pending);
            # a caller handing us any OTHER call would silently execute
            # the wrong args — fail loudly instead
            assert call is p.pending, "BATCH_OPS delegation requires " \
                "call is p.pending (args are read from there)"
            return self._exec_batch(call.op, [p], now)[p.host]
        h = p.host
        mask = self._lane(h)
        op, a = call.op, call.args

        if op == "epoll_create":
            epfd = p.next_epfd
            p.next_epfd += 1
            p.epolls[epfd] = _Epoll()
            return True, epfd
        if op == "epoll_ctl":
            epfd, ctl, fd, events = a
            ep = p.epolls.get(epfd)
            if ep is None:
                return True, -1
            if ctl in (EPOLL.CTL_ADD, EPOLL.CTL_MOD):
                if ctl == EPOLL.CTL_ADD and fd in ep.watches:
                    return True, -1       # EEXIST
                if ctl == EPOLL.CTL_MOD and fd not in ep.watches:
                    return True, -1       # ENOENT
                # MOD resets the edge base and re-arms oneshot
                # (ref: epoll.c watch flag algebra, epoll.c:24-67)
                ep.watches[fd] = _EpollWatch(
                    interest=events & (EPOLL.IN | EPOLL.OUT),
                    flags=events & (EPOLL.ET | EPOLL.ONESHOT),
                )
            elif ctl == EPOLL.CTL_DEL:
                if ep.watches.pop(fd, None) is None:
                    return True, -1       # ENOENT
            return True, 0
        if op == "epoll_wait":
            ep = p.epolls.get(a[0])
            if ep is None:
                return True, []
            events = []
            for wfd, w in ep.watches.items():
                if not w.armed:
                    continue
                report = self._watch_report(p, wfd, w)
                # consume the edge base whether or not it reported
                w.prev_in_gen, w.prev_out_gen = self._fd_gens(p, wfd)
                if report:
                    events.append((wfd, report))
                    if w.flags & EPOLL.ONESHOT:
                        w.armed = False
            if events:
                return True, events
            return False, None
        if op == "listen":
            self.sim = tcpmod.tcp_listen(self.sim, mask,
                                         jnp.full_like(mask, a[0], I32))
            self._flags_cache = None
            self._tcp_st_cache = None
            return True, 0
        if op == "gettime":
            return True, now
        if op == "gethostbyname":
            addr = self.bundle.dns.resolve_name(a[0])
            return True, (addr.ip if addr is not None else -1)
        if op == "setsockopt":
            fd, opt, val = a
            net = self.sim.net
            slot = jnp.full_like(mask, fd, I32)
            v = jnp.full(mask.shape, int(val), I32)
            if opt == SO.SNDBUF:
                net = net.replace(
                    sk_sndbuf=set_hs(net.sk_sndbuf, mask, slot, v),
                    autotune_snd=net.autotune_snd & ~mask)
            elif opt == SO.RCVBUF:
                net = net.replace(
                    sk_rcvbuf=set_hs(net.sk_rcvbuf, mask, slot, v),
                    autotune_rcv=net.autotune_rcv & ~mask)
            else:
                return True, -1
            self.sim = self.sim.replace(net=net)
            return True, 0
        if op == "getsockopt":
            fd, opt = a
            net = self.sim.net
            if opt == SO.SNDBUF:
                return True, int(net.sk_sndbuf[h, fd])
            if opt == SO.RCVBUF:
                return True, int(net.sk_rcvbuf[h, fd])
            return True, -1
        if op == "ioctl_inq":
            fd = a[0]
            net = self.sim.net
            if (int(net.sk_type[h, fd]) == SocketType.TCP
                    and self.sim.tcp is not None):
                return True, int(self.sim.tcp.app_rbytes[h, fd])
            return True, int(net.in_bytes[h, fd])
        if op == "ioctl_outq":
            fd = a[0]
            net = self.sim.net
            if (int(net.sk_type[h, fd]) == SocketType.TCP
                    and self.sim.tcp is not None):
                t = self.sim.tcp
                return True, int(t.snd_end[h, fd]) - int(t.snd_una[h, fd])
            return True, int(net.out_bytes[h, fd])
        # Blocking-syscall retries are gated on host-side cached
        # readiness, so a blocked process costs NO device dispatch per
        # window unless its call can actually progress (the batching
        # SURVEY.md §7.4.4 requires; the readiness bits are exactly
        # what the reference's epoll notify would check before
        # process_continue, epoll.c:583-680).
        if op == "connect":
            fd, ip, port = a
            st = self._tcp_st(h, fd)
            if p.block is None:
                # issue the SYN, then block until established
                self._apply(lambda sim, buf: tcpmod.tcp_connect(
                    self.cfg, sim, mask, jnp.full_like(mask, fd, I32),
                    ip, port, now, buf), now)
                return False, None
            if st == tcpmod.TcpSt.ESTABLISHED or st >= tcpmod.TcpSt.FIN_WAIT_1:
                return True, 0
            if st == tcpmod.TcpSt.CLOSED:
                return True, -1       # connection refused/reset
            return False, None
        if op == "accept":
            fd = a[0]
            # listener readable iff children are queued (tcp_accept
            # maintains the bit) — skip the device pop otherwise
            if not self._sk_flag(h, fd, SocketFlags.READABLE):
                return False, None
            child = None

            def do(sim, buf):
                nonlocal child
                sim, got, ch = tcpmod.tcp_accept(
                    sim, mask, jnp.full_like(mask, fd, I32))
                child = int(ch[h])
                return sim, buf

            self._apply(do, now)
            if child is not None and child >= 0:
                return True, child
            return False, None
        # ---- r5 surface breadth: files / random / signals ------------
        # (backend-independent, dispatched through the shared table so
        # the real-host-kernel executor runs the identical code —
        # hostrun/executor.py, docs/7-conformance.md)
        if op in SHARED_OPS:
            return SHARED_OPS[op](self.host_state, self, p, a)
        if op == "thread_create":
            gen = a[0](h)
            t = _Proc(host=h, gen=gen, start_time=now,
                      pid=self._next_pid)
            self._next_pid += 1
            self.procs.append(t)
            return True, t.pid
        if op == "thread_join":
            tgt = next((q for q in self.procs if q.pid == a[0]
                        and q.host == h), None)
            if tgt is None:
                return True, None         # ESRCH -> join returns
            if not tgt.done:
                return False, None        # block until it completes
            return True, tgt.result
        if op == "mutex_init":
            mid = self._next_mutex.get(h, 1)
            self._next_mutex[h] = mid + 1
            self._mutexes[(h, mid)] = 0
            return True, mid
        if op == "mutex_lock":
            owner = self._mutexes.get((h, a[0]))
            if owner is None:
                return True, -1           # EINVAL
            if owner and owner != p.pid:
                return False, None        # block until released
            self._mutexes[(h, a[0])] = p.pid
            return True, 0
        if op == "mutex_trylock":
            owner = self._mutexes.get((h, a[0]))
            if owner is None:
                return True, -1
            if owner and owner != p.pid:
                return True, False        # EBUSY
            self._mutexes[(h, a[0])] = p.pid
            return True, True
        if op == "mutex_unlock":
            if self._mutexes.get((h, a[0])) != p.pid:
                return True, -1            # EPERM
            self._mutexes[(h, a[0])] = 0
            return True, 0
        if op == "cond_init":
            cid = self._next_cond.get(h, 1)
            self._next_cond[h] = cid + 1
            # OrderedDict-by-construction: pid -> signaled flag,
            # insertion order = FIFO wakeup order (rpth pth_cond_await
            # enqueues waiters and pth_cond_notify releases them
            # oldest-first, pth_high.c)
            self._conds[(h, cid)] = {}
            return True, cid
        if op == "cond_wait":
            cid, mid = a
            waiters = self._conds.get((h, cid))
            if waiters is None:
                return True, -1            # EINVAL
            if p.block is None:
                # first entry: atomically release the mutex and join
                # the wait queue (pthread_cond_wait contract; EPERM if
                # the caller does not hold the mutex)
                if self._mutexes.get((h, mid)) != p.pid:
                    return True, -1        # EPERM
                self._mutexes[(h, mid)] = 0
                # the release may unblock a parked mutex_lock even
                # though cond_wait itself returns blocked — make sure
                # _resume_all re-sweeps (see _chan_kick)
                self._chan_kick = True
                waiters[p.pid] = False
                return False, None
            if not waiters.get(p.pid, False):
                return False, None         # not signaled yet
            # signaled: re-acquire the mutex before returning (the
            # second half of pthread_cond_wait); stay blocked while
            # another thread holds it
            owner = self._mutexes.get((h, mid))
            if owner and owner != p.pid:
                return False, None
            self._mutexes[(h, mid)] = p.pid
            del waiters[p.pid]
            return True, 0
        if op == "cond_signal":
            waiters = self._conds.get((h, a[0]))
            if waiters is None:
                return True, -1            # EINVAL
            for pid, sig in waiters.items():
                if not sig:               # oldest unsignaled waiter
                    waiters[pid] = True
                    break
            return True, 0
        if op == "cond_broadcast":
            waiters = self._conds.get((h, a[0]))
            if waiters is None:
                return True, -1            # EINVAL
            for pid in waiters:
                waiters[pid] = True
            return True, 0
        if op == "pipe":
            base = self._next_pipe_fd.setdefault(h, PIPE_FD_BASE)
            rfd, wfd = base, base + 1
            self._next_pipe_fd[h] = base + 2
            q = _ByteQ()
            self._channels[(h, rfd)] = _ChanEnd(recv_q=q)
            self._channels[(h, wfd)] = _ChanEnd(send_q=q)
            return True, (rfd, wfd)
        if op == "socketpair":
            base = self._next_pipe_fd.setdefault(h, PIPE_FD_BASE)
            fd1, fd2 = base, base + 1
            self._next_pipe_fd[h] = base + 2
            qa, qb = _ByteQ(), _ByteQ()
            self._channels[(h, fd1)] = _ChanEnd(recv_q=qa, send_q=qb)
            self._channels[(h, fd2)] = _ChanEnd(recv_q=qb, send_q=qa)
            return True, (fd1, fd2)
        if op == "write":
            fd, data = a
            if fd in (1, 2):
                # per-process stdout/stderr (ref: process.c's
                # <data>/hosts/<name>/<plugin>.stdout files)
                return True, stdio_write(self.host_state,
                                         self.bundle.host_names[h],
                                         h, p.pid, fd, data)
            if FILE_FD_BASE <= fd < TIMER_FD_BASE:
                return True, file_write(self.host_state, h, fd, data)
            ep = self._channels.get((h, fd))
            if ep is None or ep.send_q is None:
                return True, -1          # EBADF
            q = ep.send_q
            if q.readers == 0:
                return True, -1          # EPIPE (ref: channel write to
                                         # a closed read end)
            space = q.cap - len(q.buf)
            if space <= 0:
                return False, None       # block until a reader drains
            n = min(space, len(data))
            q.buf.extend(data[:n])
            q.in_gen += 1
            return True, n
        if op == "read":
            fd, maxb = a
            if FILE_FD_BASE <= fd < TIMER_FD_BASE:
                return True, file_read(self.host_state, h, fd, maxb)
            ep = self._channels.get((h, fd))
            if ep is None or ep.recv_q is None:
                return True, b""         # EBADF-ish: nothing to read
            q = ep.recv_q
            if q.buf:
                n = min(maxb, len(q.buf))
                out = bytes(q.buf[:n])
                del q.buf[:n]
                q.out_gen += 1
                return True, out
            if q.writers == 0:
                return True, b""         # EOF: all write ends closed
            return False, None
        if op == "timerfd_create":
            nxt = self._timer_alloc.get(h, 0)
            if nxt >= self.cfg.timers_per_host:
                return True, -1
            self._timer_alloc[h] = nxt + 1
            return True, TIMER_FD_BASE + nxt
        if op == "timerfd_settime":
            tfd, expire, interval = a
            slot = jnp.full_like(mask, tfd - TIMER_FD_BASE, I32)
            from shadow_tpu.net import timers as timermod

            if expire == 0:
                self.sim = timermod.timer_disarm(self.sim, mask, slot)
                return True, 0
            # timerfd(2) default semantics: it_value is RELATIVE to
            # now (no TFD_TIMER_ABSTIME on the surface — the
            # reference's timer_setTime converts the same way,
            # timer.c); timer_set itself takes an absolute deadline
            self._apply(lambda sim, buf: timermod.timer_set(
                sim, buf, mask, slot, now + expire, interval), now)
            return True, 0
        if op == "timerfd_read":
            tfd = a[0]
            ts = tfd - TIMER_FD_BASE
            n = int(self.sim.net.tm_expirations[h, ts])
            if n == 0:
                return False, None
            from shadow_tpu.net import timers as timermod

            slot = jnp.full_like(mask, ts, I32)
            sim2, cnt = timermod.timer_read(self.sim, mask, slot)
            self.sim = sim2
            self._timer_reads[(h, ts)] = \
                self._timer_reads.get((h, ts), 0) + 1
            return True, int(cnt[h])
        if op == "shutdown":
            fd, how = a
            if how in (SHUT_WR, SHUT_RDWR) \
                    and int(self.sim.net.sk_type[h, fd]) == SocketType.TCP:
                self._apply(lambda sim, buf: tcpmod.tcp_close(
                    self.cfg, sim, mask, jnp.full_like(mask, fd, I32),
                    now, buf), now)
            return True, 0
        if op == "sleep":
            if p.block is None:
                p.wake_time = now + int(a[0])
                return False, None
            if now >= p.wake_time:
                return True, 0
            return False, None
        if op == "wait_readable":
            ready = [fd for fd in a[0] if self._fd_ready(p, fd) & EPOLL.IN]
            if ready:
                return True, ready
            return False, None
        if op in ("poll", "select"):
            # level-triggered readiness scans over the same status
            # engine epoll uses (ref: host_select/host_poll,
            # host.c:852-1009 — both walk the descriptor table and
            # test READABLE/WRITABLE). Timeout rides the sleep
            # machinery: wake_time is armed on first block and a
            # timed-out wait returns the empty result.
            if op == "poll":
                revs = [(fd, self._fd_ready(p, fd) & ev)
                        for fd, ev in a[0]]
                result = [(fd, r) for fd, r in revs if r]
                got = bool(result)
                empty = []
            else:
                r = [fd for fd in a[0]
                     if self._fd_ready(p, fd) & EPOLL.IN]
                w = [fd for fd in a[1]
                     if self._fd_ready(p, fd) & EPOLL.OUT]
                result = (r, w)
                got = bool(r or w)
                empty = ([], [])
            timo = a[-1]
            if got:
                return True, result
            if timo == 0:
                return True, empty
            if timo > 0:
                if p.block is None:
                    p.wake_time = now + timo
                elif now >= p.wake_time:
                    return True, empty
            return False, None
        raise ValueError(f"unknown syscall {op}")

    # -- batched syscall execution (SURVEY §7.4.4) ----------------------
    # Data-plane ops whose device kernel is a masked [H] batch update:
    # N processes on N distinct hosts issuing the same op in the same
    # scheduler round execute as ONE fused device op with a multi-hot
    # mask and per-host argument vectors — the per-window syscall
    # batching the reference gets for free from shared memory and we
    # need to amortize device dispatch latency (VERDICT r2 weak #6:
    # O(procs x syscalls) dispatches walled any 1000-vproc config).

    BATCH_OPS = frozenset((
        "sendto", "sendto_data", "recvfrom", "recvfrom_data",
        "recv", "recv_data", "send", "send_data",
        "socket", "bind", "close",
    ))

    def _batch_arrays(self, group, cols, dtypes=None):
        """mask + [H] arg arrays from a {host: args-tuple} group.
        `cols` = indices into each args tuple to vectorize; `dtypes`
        per column (default i32, matching the serial path's
        jnp.full_like(mask, v, I32) slots; IPs need i64)."""
        H = self.cfg.num_hosts
        m = np.zeros(H, bool)
        dts = dtypes or [np.int32] * len(cols)
        out = [np.zeros(H, dt) for dt in dts]
        for h, a in group.items():
            m[h] = True
            for i, c in enumerate(cols):
                out[i][h] = a[c]
        return (jnp.asarray(m),) + tuple(jnp.asarray(x) for x in out)

    def _exec_batch(self, op: str, procs: list, now: int) -> dict:
        """Execute one op kind for processes on DISTINCT hosts as one
        fused device op. Returns {host: (ready, result)} with results
        identical to per-host _exec (same kernels, multi-hot mask).
        Host-side work (payload pool, stream FIFOs) runs per host in
        the caller's batch order, which the scheduler builds by sorted
        host id — PER-HOST ordering is exactly the serial path's, but
        CROSS-host side-effect order (e.g. pool-ref assignment) is
        host-sorted rather than global spawn order. Deterministic
        either way; per-host state is bitwise unaffected."""
        res: dict = {}

        if op in ("sendto", "sendto_data"):
            # non-blocking datagram sends; pool puts first (spawn order)
            group = {}
            prefs = {}
            for p in procs:
                fd, ip, port, last = p.pending.args
                if op == "sendto_data":
                    prefs[p.host] = self.pool.put(bytes(last))
                    group[p.host] = (fd, ip, port, len(last),
                                     prefs[p.host])
                else:
                    group[p.host] = (fd, ip, port, last, -1)
            mask, fd, ip, port, n, pref = self._batch_arrays(
                group, (0, 1, 2, 3, 4),
                dtypes=(np.int32, np.int64, np.int32, np.int32, np.int32))
            ok = None

            def do(sim, buf):
                nonlocal ok
                net, okk = udpmod.udp_enqueue_send(
                    sim.net, mask, fd, ip, port, n, pref)
                ok = okk
                from shadow_tpu.net import nic
                return nic.notify_wants_send(
                    sim.replace(net=net), buf, okk, now)

            self._apply(do, now)
            ok = np.asarray(ok)
            for p in procs:
                queued = bool(ok[p.host])
                if op == "sendto_data" and not queued:
                    self.pool.unref(prefs[p.host])  # EWOULDBLOCK
                res[p.host] = (True, queued)
            return res

        if op in ("recvfrom", "recvfrom_data", "recv", "recv_data"):
            # blocked unless READABLE (host-side cache, no dispatch)
            ready_procs = []
            for p in procs:
                fd = p.pending.args[0]
                if self._sk_flag(p.host, fd, SocketFlags.READABLE):
                    ready_procs.append(p)
                else:
                    res[p.host] = (False, None)
            # split TCP stream reads from UDP datagram reads ("recv"
            # on a TCP fd is a stream read; "recv_data" is stream-only
            # by contract — both exactly as serial _exec routes them).
            # ONE sk_type snapshot for the whole batch, not a device
            # indexing read per process.
            tcp_grp, udp_grp = [], []
            sktype = (np.asarray(self.sim.net.sk_type)
                      if ready_procs and op == "recv" else None)
            for p in ready_procs:
                fd = p.pending.args[0]
                is_tcp = op == "recv_data" or (
                    op == "recv" and self.sim.tcp is not None and (
                        int(sktype[p.host, fd]) == SocketType.TCP
                        or self._tcp_st(p.host, fd) != 0))
                (tcp_grp if is_tcp else udp_grp).append(p)

            if tcp_grp:
                group = {p.host: (p.pending.args[0],
                                  p.pending.args[1] if
                                  len(p.pending.args) > 1 else 1 << 30)
                         for p in tcp_grp}
                mask, fd, maxb = self._batch_arrays(group, (0, 1))
                got = {}

                def dot(sim, buf):
                    sim, buf, nr, ef = tcpmod.tcp_recv(
                        sim, mask, fd, maxb, now, buf)
                    got["nr"], got["ef"] = nr, ef
                    return sim, buf

                self._apply(dot, now)
                nr = np.asarray(got["nr"])
                ef = np.asarray(got["ef"])
                for p in tcp_grp:
                    h = p.host
                    nread, eof = int(nr[h]), bool(ef[h])
                    if nread > 0:
                        if op == "recv":
                            res[h] = (True, nread)
                        else:
                            key = self._stream_key(
                                p, p.pending.args[0], sending=False)
                            fifo = self._streams.get(key)
                            if fifo is None or len(fifo) < nread:
                                have = bytes(fifo[:nread]) if fifo else b""
                                out = have + b"\x00" * (nread - len(have))
                                if fifo:
                                    del fifo[:len(have)]
                            else:
                                out = bytes(fifo[:nread])
                                del fifo[:nread]
                            res[h] = (True, out)
                    elif eof:
                        res[h] = (True, 0 if op == "recv" else b"")
                    else:
                        res[h] = (False, None)

            if udp_grp:
                group = {p.host: (p.pending.args[0],) for p in udp_grp}
                mask, fd = self._batch_arrays(group, (0,))
                got = {}

                def dou(sim, buf):
                    net, g, sip, spt, ln, pr = udpmod.udp_recv(
                        sim.net, mask, fd)
                    got.update(g=g, sip=sip, spt=spt, ln=ln, pr=pr)
                    return sim.replace(net=net), buf

                self._apply(dou, now)
                g = np.asarray(got["g"])
                sip = np.asarray(got["sip"])
                spt = np.asarray(got["spt"])
                ln = np.asarray(got["ln"])
                pr = np.asarray(got["pr"])
                for p in udp_grp:
                    h = p.host
                    if not bool(g[h]):
                        res[h] = (False, None)
                        continue
                    pref = int(pr[h])
                    if op == "recvfrom_data":
                        if pref >= 0:
                            data = self.pool.get(pref)
                            self.pool.unref(pref)
                        else:
                            data = b"\x00" * int(ln[h])
                        res[h] = (True, (int(sip[h]), int(spt[h]), data))
                    else:
                        if pref >= 0:
                            self.pool.unref(pref)  # length-only API
                        if op == "recvfrom":
                            res[h] = (True, (int(sip[h]), int(spt[h]),
                                             int(ln[h])))
                        else:          # "recv" on a UDP fd
                            res[h] = (True, int(ln[h]))
            return res

        if op in ("send", "send_data"):
            ready_procs = []
            for p in procs:
                fd = p.pending.args[0]
                if self._sk_flag(p.host, fd, SocketFlags.WRITABLE):
                    ready_procs.append(p)
                else:
                    res[p.host] = (False, None)
            if ready_procs:
                group = {}
                for p in ready_procs:
                    fd, last = p.pending.args
                    n = len(last) if op == "send_data" else last
                    group[p.host] = (fd, n)
                mask, fd, n = self._batch_arrays(group, (0, 1))
                got = {}

                def dos(sim, buf):
                    sim, buf, accepted = tcpmod.tcp_send(
                        self.cfg, sim, mask, fd, n, now, buf)
                    got["acc"] = accepted
                    return sim, buf

                self._apply(dos, now)
                acc = np.asarray(got["acc"])
                for p in ready_procs:
                    h = p.host
                    a = int(acc[h])
                    if a > 0:
                        if op == "send_data":
                            key = self._stream_key(
                                p, p.pending.args[0], sending=True)
                            self._streams.setdefault(
                                key, bytearray()).extend(
                                    p.pending.args[1][:a])
                        res[h] = (True, a)
                    else:
                        res[h] = (False, None)
            return res

        if op == "socket":
            group = {p.host: (p.pending.args[0],) for p in procs}
            mask, stype = self._batch_arrays(group, (0,))
            self.stat_device_dispatches += 1
            net, slot = sk_create(self.sim.net, mask, stype)
            self.sim = self.sim.replace(net=net)
            self._flags_cache = None
            self._tcp_st_cache = None
            s = np.asarray(slot)
            return {p.host: (True, int(s[p.host])) for p in procs}

        if op == "bind":
            # host-side EINVAL / EADDRINUSE checks from ONE snapshot
            # (the serial path's per-bind int() reads cost one device
            # sync each — ADVICE r2 #4), then one fused sk_bind
            net = self.sim.net
            bound = np.asarray(net.sk_bound_port)
            sktype = np.asarray(net.sk_type)
            S = bound.shape[1]
            group = {}
            ok_procs = []
            for p in procs:
                fd, want = p.pending.args[0], int(p.pending.args[1])
                h = p.host
                if int(bound[h, fd]) != 0:
                    res[h] = (True, -1)        # EINVAL: already bound
                    continue
                if want != 0:
                    proto = int(sktype[h, fd])
                    taken = bool(np.any(
                        (sktype[h] == proto) & (bound[h] == want)
                        & (np.arange(S) != fd)))
                    if taken:
                        res[h] = (True, -1)    # EADDRINUSE
                        continue
                group[h] = (fd, want)
                ok_procs.append(p)
            if group:
                mask, fd, want = self._batch_arrays(group, (0, 1))
                self.stat_device_dispatches += 1
                net2, port = sk_bind(net, mask, fd, 0, want)
                self.sim = self.sim.replace(net=net2)
                self._flags_cache = None
                self._tcp_st_cache = None
                prt = np.asarray(port)
                for p in ok_procs:
                    res[p.host] = (True, int(prt[p.host]))
            return res

        if op == "close":
            # pipe/timer/epoll closes are pure host-side bookkeeping
            # (no device dispatch); socket closes split into one
            # tcp_close and one fused UDP slot clear
            tcp_grp, udp_grp = [], []
            sktype = np.asarray(self.sim.net.sk_type)
            for p in procs:
                fd = p.pending.args[0]
                if fd >= EPOLL_FD_BASE:        # pipes/timers/epolls too
                    res[p.host] = self._close_special(p, fd)
                    continue
                for ep in p.epolls.values():
                    ep.watches.pop(fd, None)
                if int(sktype[p.host, fd]) == SocketType.TCP:
                    tcp_grp.append(p)
                else:
                    udp_grp.append(p)
            if tcp_grp:
                group = {p.host: (p.pending.args[0],) for p in tcp_grp}
                mask, fd = self._batch_arrays(group, (0,))
                self._apply(lambda sim, buf: tcpmod.tcp_close(
                    self.cfg, sim, mask, fd, now, buf), now)
                for p in tcp_grp:
                    res[p.host] = (True, 0)
            if udp_grp:
                group = {p.host: (p.pending.args[0],) for p in udp_grp}
                sel, slot = self._batch_arrays(group, (0,))
                self.stat_device_dispatches += 1
                net = self.sim.net
                was_live = sel & (gather_hs(net.sk_type, slot)
                                  != SocketType.NONE)
                net = net.replace(
                    sk_type=set_hs(net.sk_type, sel, slot,
                                   jnp.zeros_like(slot)),
                    sk_flags=set_hs(net.sk_flags, sel, slot,
                                    jnp.zeros_like(slot)),
                    sk_bound_port=set_hs(net.sk_bound_port, sel, slot,
                                         jnp.zeros_like(slot)),
                    ctr_sk_free=net.ctr_sk_free
                    + was_live.astype(jnp.int64),
                )
                self.sim = self.sim.replace(net=net)
                self._flags_cache = None
                self._tcp_st_cache = None
                for p in udp_grp:
                    res[p.host] = (True, 0)
            return res

        raise ValueError(f"op {op} is not batchable")

    # -- r5 surface-breadth helpers -------------------------------------
    # (files / random / stdio moved to module level — file_open,
    # file_write, file_read, stdio_write, host_rand — so hostrun's
    # real-kernel executor shares them via SHARED_OPS)

    def _deliver_signal(self, p: _Proc, sig: int) -> int:
        """Run the installed handler host-side (the pth-dispatched
        handler analog); an unhandled signal kills the process like a
        plugin fault (slave.c:468-473)."""
        handler = p.sig_handlers.get(sig)
        if handler is None:
            p.gen.close()
            p.done = True
            p.pending = None
            p.block = None
            if self.trace is not None:
                self.trace.record_exit(p.host, p.pid, ("killed", sig))
            return -1
        handler(sig)
        return 0

    def stdio_of(self, host: int, pid: int, fd: int = 1) -> bytes:
        return bytes(self._stdio.get((host, pid, fd), b""))

    def _close_special(self, p: _Proc, fd: int):
        """close() of a non-socket fd: pipe/socketpair ends (status
        flips for the peer — last writer gone -> reader sees EOF,
        last reader gone -> writer sees EPIPE, ref: channel.c
        close/free), an epoll descriptor, or a virtual file. Pure
        host-side."""
        h = p.host
        if FILE_FD_BASE <= fd < TIMER_FD_BASE:
            return (True,
                    0 if self._file_fds.pop((h, fd), None) is not None
                    else -1)
        if fd >= PIPE_FD_BASE:
            ep = self._channels.pop((h, fd), None)
            for epl in p.epolls.values():
                epl.watches.pop(fd, None)
            if ep is not None:
                if ep.recv_q is not None:
                    ep.recv_q.readers -= 1
                    ep.recv_q.out_gen += 1
                if ep.send_q is not None:
                    ep.send_q.writers -= 1
                    ep.send_q.in_gen += 1
            return True, 0
        p.epolls.pop(fd, None)
        return True, 0

    # -- scheduler ------------------------------------------------------

    def _resume_all(self, now: int) -> None:
        """Advance every runnable coroutine until all are blocked
        (the pth_yield loop, process.c:1227-1229), in breadth-first
        ROUNDS so data-plane syscalls from distinct hosts fuse into
        one device op each (_exec_batch; SURVEY §7.4.4). Each round
        claims the earliest runnable process per host (per-host spawn
        order — one host's syscalls stay strictly serialized, the
        per-host determinism contract), executes non-batchable ops in
        spawn order, then each batchable op kind as one fused masked
        op. A process that blocks is parked for the rest of the sweep
        (the serial loop visited each process once per sweep too).

        Sweeps repeat while channel activity occurred: a pipe
        write/read/close by a later process can unblock an earlier
        one at the same instant (the reference's status-change notify
        re-enters process_continue within the same sim time,
        epoll.c:583-680). Only channels need this — every other
        cross-process path rides device events, which land in a
        later window."""
        # ops whose completion can UNBLOCK another parked coroutine on
        # the same host (channel byte movement, mutex handover) — they
        # trigger another sweep, exactly like pth's scheduler re-runs
        # ready green threads until quiescence
        chan_ops = ("pipe", "socketpair", "write", "read",
                    "mutex_unlock", "thread_create",
                    # cond_signal/broadcast wake parked cond_waits;
                    # cond_wait's completion re-acquires (and its first
                    # entry releases — see _chan_kick) the mutex
                    "cond_signal", "cond_broadcast", "cond_wait",
                    # an unhandled signal kills its target directly
                    # (_deliver_signal), which can complete a proc a
                    # parked thread_join is waiting on
                    "kill", "raise_sig")
        # syscalls whose blocking state channel activity can change;
        # later sweeps retry ONLY processes blocked on these (cheap,
        # host-side) — re-running device-side blocked ops (tcp_send,
        # accept, ...) every sweep would cost a device dispatch per
        # blocked process per sweep for state that cannot have changed
        retry_ops = ("read", "write", "wait_readable", "epoll_wait",
                     "poll", "select", "thread_join", "mutex_lock",
                     "cond_wait")

        def advance(p, idx, ready, result, parked):
            """Feed one syscall result back into its coroutine."""
            call = p.pending
            if not ready:
                p.block = call
                parked.add(idx)
                return False
            if call.op in chan_ops or (
                    call.op == "close" and call.args
                    and call.args[0] >= PIPE_FD_BASE):
                advance.chan_activity = True
            if self.trace is not None:
                # conformance hook: every COMPLETED syscall (blocked
                # retries are invisible, matching the host backend
                # where a blocking call is one real syscall)
                self.trace.record(p.host, p.pid, call.op, call.args,
                                  result)
            p.block = None
            try:
                p.pending = p.gen.send(result)
            except StopIteration as e:
                p.done = True
                p.pending = None
                p.result = e.value
                if self.trace is not None:
                    self.trace.record_exit(p.host, p.pid, p.result)
                # a completed coroutine unblocks thread_join waiters —
                # that's sweep-worthy activity
                advance.chan_activity = True
            return True

        sweep = 0
        while True:
            advance.chan_activity = False
            parked: set = set()           # proc indices blocked this sweep
            while True:                   # rounds
                claimed: dict = {}        # host -> (idx, proc)
                for idx, p in enumerate(self.procs):
                    if p.done or now < p.start_time or idx in parked:
                        continue
                    if sweep > 0 and p.block is not None \
                            and p.block.op not in retry_ops:
                        continue
                    if p.host in claimed:
                        continue
                    claimed[p.host] = (idx, p)
                if not claimed:
                    break
                progress = False
                parked_before = len(parked)
                batches: dict = {}
                serial = []
                for h in sorted(claimed):
                    idx, p = claimed[h]
                    if not p.started:
                        p.started = True
                        try:
                            p.pending = next(p.gen)
                        except StopIteration as e:
                            p.done = True
                            p.result = e.value
                            if self.trace is not None:
                                self.trace.record_exit(p.host, p.pid,
                                                       p.result)
                            # a finished process IS progress: its host
                            # is claimable by a successor next round —
                            # and sweep-worthy activity (a same-host
                            # thread_join parked earlier this sweep
                            # must see the completion)
                            progress = True
                            advance.chan_activity = True
                            continue
                        p.block = None
                    if p.pending is None:
                        p.done = True
                        progress = True
                        continue
                    if p.pending.op in self.BATCH_OPS:
                        batches.setdefault(p.pending.op, []).append((idx, p))
                    else:
                        serial.append((idx, p))
                for idx, p in sorted(serial):
                    ready, result = self._exec(p, p.pending, now)
                    self.stat_syscalls += 1
                    progress |= advance(p, idx, ready, result, parked)
                for op in sorted(batches):
                    lst = batches[op]
                    results = self._exec_batch(op, [p for _, p in lst], now)
                    self.stat_syscalls += len(lst)
                    for idx, p in lst:
                        ready, result = results[p.host]
                        progress |= advance(p, idx, ready, result, parked)
                # a newly-parked process changes the next round's
                # claims (a same-host successor becomes claimable), so
                # parking counts as progress for loop continuation
                if not progress and len(parked) == parked_before:
                    break
            sweep += 1
            # cond_wait's first entry releases its mutex but itself
            # returns blocked — advance() never sees a ready result,
            # so fold the _exec-side kick in here
            if self._chan_kick:
                advance.chan_activity = True
                self._chan_kick = False
            if not advance.chan_activity:
                break

    def gc_pool(self) -> int:
        """Mark-sweep the payload pool against the device state: a
        pool entry is live iff its id appears in any in-flight packet
        location (event queue words, outbox words, router ring, socket
        output rings, or input rings). Entries dropped inside the
        simulated network (reliability/CoDel/no-socket/rcvbuf drops
        destroy the packet on device, where the host cannot observe
        the unref — the reference unrefs in packet_unref, packet.c)
        are collected here. Returns the number of entries released."""
        from shadow_tpu.core import simtime as st
        from shadow_tpu.net import packetfmt as pfm

        sim = self.sim
        live: set[int] = set()

        def ring_live(payref, head, count):
            """payrefs at live ring positions [head, head+count)."""
            B = payref.shape[-1]
            idx = np.arange(B)
            mask = ((idx - head[..., None]) % B) < count[..., None]
            return payref[mask]

        def mark(vals):
            live.update(int(x) for x in np.unique(vals) if x >= 0)

        mark(np.asarray(sim.events.words)[..., pfm.W_PAYREF][
            np.asarray(sim.events.time) != st.INVALID])
        mark(np.asarray(sim.outbox.words)[..., pfm.W_PAYREF][
            np.asarray(sim.outbox.dst) >= 0])
        net = sim.net
        mark(ring_live(np.asarray(net.rq_words)[..., pfm.W_PAYREF],
                       np.asarray(net.rq_head), np.asarray(net.rq_count)))
        mark(ring_live(np.asarray(net.out_words)[..., pfm.W_PAYREF],
                       np.asarray(net.out_head), np.asarray(net.out_count)))
        mark(ring_live(np.asarray(net.in_payref),
                       np.asarray(net.in_head), np.asarray(net.in_count)))
        freed = 0
        for pid in self.pool.live_ids():
            if pid not in live:
                while self.pool.unref(pid) > 0:
                    pass
                freed += 1
        return freed

    def run(self, end_time: int | None = None, on_window=None):
        """The master window loop (ref: master.c:450-480 +
        slave.c:413-466) with coroutine continuation between windows.
        `on_window(sim, wend)` runs after every device window — pcap
        drains, heartbeats, progress hooks (mirrors
        checkpoint.run_windows)."""
        end = end_time if end_time is not None else self.cfg.end_time
        min_jump = max(int(self.bundle.min_jump), 1)
        # host-side twin of the record-time wend clamp (engine.make_wend_fn
        # / checkpoint.run_windows): fault records take effect exactly at
        # their timestamps, never early because a window crossed one.
        from shadow_tpu.net.build import plan_times

        _pt = plan_times(self.bundle)

        total = EngineStats.create()
        now = 0
        while now <= end:
            # stoptime enforcement (ref: process_stop,
            # process.c:1286-1324): kill before resuming, so a
            # stopped process never runs at or past its stop time
            for p in self.procs:
                if not p.done and 0 <= p.stop_time <= now:
                    p.gen.close()
                    p.done = True
                    p.pending = None
                    p.block = None
            self._resume_all(now)

            # next window start: earliest of device events, sleep
            # deadlines, not-yet-started process start times, and
            # pending stop deadlines
            cands = [int(jnp.min(self.sim.events.min_time()))]
            cands += [p.wake_time for p in self.procs
                      if not p.done and p.block is not None
                      and (p.block.op == "sleep"
                           or (p.block.op in ("poll", "select")
                               and p.block.args[-1] > 0))]
            cands += [p.start_time for p in self.procs
                      if not p.done and not p.started]
            cands += [p.stop_time for p in self.procs
                      if not p.done and p.stop_time >= 0]
            # never step backward: a (buggy or already-fired) event
            # timestamped before `now` must not rewind the clock —
            # the engine's own advance rule clamps the same way
            # (engine.run: first = max(min, start_time))
            wstart = max(min(c for c in cands if c >= 0), now)
            if wstart > end or wstart >= simtime.INVALID:
                break
            if wstart > now:
                # jump to the next deadline and resume THERE, before
                # running any device window: process starts, sleep
                # wakes, and stop kills happen at their exact sim
                # times (the reference schedules each as an event at
                # that time — process.c:1326-1360; a window-end
                # resume would make every one late by min_jump)
                now = int(wstart)
                continue
            wend = min(wstart + min_jump, end + 1)
            if _pt is not None:
                i = int(np.searchsorted(_pt, wstart, side="right"))
                if i < len(_pt):
                    wend = min(wend, int(_pt[i]))
            self.sim, stats, next_min = self._jit_window(
                self.sim, wstart, wend)
            # the device window mutated readiness state (flags/gens):
            # drop the host-side snapshot or blocked epoll_wait /
            # wait_readable polls read stale readiness forever
            self._flags_cache = None
            self._tcp_st_cache = None
            if on_window is not None:
                on_window(self.sim, wend)
            total = total.replace(
                events_processed=total.events_processed
                + stats.events_processed,
                micro_steps=total.micro_steps + stats.micro_steps,
                windows=total.windows + 1,
                fastpath_hit=total.fastpath_hit + stats.fastpath_hit,
                fastpath_miss=total.fastpath_miss + stats.fastpath_miss,
            )
            now = int(wend)
        # collect payload-pool entries whose packets died on device
        # (drops destroy packets where the host cannot unref —
        # the packet_unref analog, packet.c)
        self.gc_pool()
        return self.sim, total
