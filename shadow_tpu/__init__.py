"""shadow_tpu — a TPU-native parallel discrete-event network simulator.

A ground-up reimplementation of the capabilities of Shadow 1.x
(reference: whzhe51/shadow) as JAX/XLA device programs:

- Simulated time is int64 nanoseconds (ref: definitions.h:18).
- Events live in per-host fixed-capacity device tensors instead of
  locked heaps (ref: priority_queue.c, scheduler_policy_host_single.c);
  the deterministic total order (time, dstHost, srcHost, seq)
  (ref: event.c:110-153) is preserved exactly.
- The conservative window barrier (ref: master.c:450-480,
  scheduler.c:359-414) becomes a min-reduction over queue heads; on a
  multi-chip mesh it is a cross-shard pmin.
- Routing is a precomputed dense latency/reliability matrix
  (ref: topology.c lazy Dijkstra cache) — a pure gather at send time.
- Protocol state (TCP/UDP/NIC/router) is struct-of-arrays, updated by
  masked vectorized handlers (ref: src/main/host/descriptor/*).

Applications run against an explicit virtual-process API (coroutines on
the host CPU, or compiled state machines on device) instead of Shadow's
elf-loader/LD_PRELOAD native-binary interposition, which cannot exist on
a TPU (ref: SURVEY.md §7.1).
"""

import jax

# Simulated time is 64-bit nanoseconds throughout (ref: definitions.h:18).
# This must be set before any jax computation in this process.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
