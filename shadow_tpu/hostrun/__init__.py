"""Dual-mode conformance subsystem: execute the SAME vproc generator
programs on the real host kernel and diff their normalized syscall
traces against the simulation's (docs/7-conformance.md).

Layout:
- kernel.py   — real-OS primitives: deterministic port mapping,
                portable timerfd stand-in
- executor.py — HostKernelExecutor: one OS thread per virtual
                process, real sockets/epoll/pipes on localhost
- trace.py    — TraceRecorder + normalization (both backends attach
                the same recorder via `runtime.trace`)
- diff.py     — differential checker over normalized traces
- runner.py   — workload catalog + one-call dual runs
"""

from .diff import DiffResult, diff_traces, render
from .executor import HostKernelExecutor
from .kernel import HostTimer, PortAllocator, PortMap, PortsUnavailable
from .runner import (DUAL_WORKLOADS, FAST_DUAL_WORKLOADS,
                     SIM_ONLY_WORKLOADS, WORKLOADS, DualResult,
                     conformance_block, run_dual, run_host, run_sim)
from .trace import TraceRecorder, load

__all__ = [
    "DiffResult", "diff_traces", "render",
    "HostKernelExecutor",
    "HostTimer", "PortAllocator", "PortMap", "PortsUnavailable",
    "DUAL_WORKLOADS", "FAST_DUAL_WORKLOADS", "SIM_ONLY_WORKLOADS",
    "WORKLOADS", "DualResult", "conformance_block",
    "run_dual", "run_host", "run_sim",
    "TraceRecorder", "load",
]
