"""Real-kernel primitives for the host-run backend: deterministic
localhost port mapping and a portable timerfd stand-in.

The conformance executor (hostrun/executor.py) presents programs the
SAME virtual namespace the simulation does — simulated IP ints,
program-level port numbers, vproc fd bases — and maps them onto real
OS resources here. Keeping the mapping deterministic (seed-derived
candidate ports, sticky (vhost, vport, proto) -> real-port
assignments) is what lets bind conflicts surface as real EADDRINUSE
exactly where the simulation reports them, and lets traces normalize
without per-run noise (docs/7-conformance.md).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time


class PortsUnavailable(RuntimeError):
    """The sandbox has no bindable localhost ports (or no loopback at
    all). Tests catch this and pytest.skip instead of flaking."""


class PortAllocator:
    """Deterministic candidate-port source with collision retry.

    Candidates are a seed-derived permutation of [base, base+span), so
    two runs of one seed probe the same sequence (stable real ports ->
    stable traces), while parallel pytest workers with different seeds
    land in different parts of the range. A candidate is validated by
    actually binding a probe socket; busy ports are skipped, and the
    executor retries through `next_port` if it loses the (tiny)
    probe-to-bind race.
    """

    def __init__(self, seed: int = 1, base: int = 23000, span: int = 20000,
                 max_probes: int = 512):
        import numpy as np

        self._rng = np.random.default_rng(
            np.random.SeedSequence([int(seed), 0x9047]))
        self.base, self.span = base, span
        self.max_probes = max_probes
        self._issued: set[int] = set()

    def _candidates(self):
        while True:
            yield self.base + int(self._rng.integers(0, self.span))

    @staticmethod
    def _probe(port: int, proto: int) -> bool:
        try:
            s = socket.socket(socket.AF_INET, proto)
        except OSError:
            raise PortsUnavailable("cannot create AF_INET sockets")
        try:
            s.bind(("127.0.0.1", port))
            return True
        except OSError:
            return False
        finally:
            s.close()

    def next_port(self, proto: int = socket.SOCK_STREAM) -> int:
        """A fresh localhost port that was free at probe time."""
        probes = 0
        for cand in self._candidates():
            if cand in self._issued:
                continue
            probes += 1
            if probes > self.max_probes:
                raise PortsUnavailable(
                    f"no free localhost port after {self.max_probes} probes")
            if self._probe(cand, proto):
                self._issued.add(cand)
                return cand

    @staticmethod
    def preflight() -> None:
        """Raise PortsUnavailable if loopback binding is impossible at
        all (no-network sandboxes) — the cheap check tests gate on."""
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        except OSError as e:
            raise PortsUnavailable(str(e))
        try:
            s.bind(("127.0.0.1", 0))
        except OSError as e:
            raise PortsUnavailable(str(e))
        finally:
            s.close()


class PortMap:
    """Sticky (vhost, vport, proto) -> real localhost port map shared
    by every process of a run.

    Stickiness is the conflict semantics: the second socket binding
    the same virtual (host, port) is pointed at the SAME real port,
    so the real kernel answers EADDRINUSE just like the simulated
    table does (_host_isInterfaceAvailable, host.c:1029-1052). The
    reverse map recovers (vhost, vport) from a real peer address for
    recvfrom/getpeername-shaped results.
    """

    def __init__(self, alloc: PortAllocator):
        self.alloc = alloc
        self._fwd: dict[tuple, int] = {}    # (vhost, vport, proto) -> real
        self._rev: dict[tuple, tuple] = {}  # (real, proto) -> (vhost, vport)
        self._lock = threading.Lock()

    def real_port(self, vhost: int, vport: int, proto: int) -> int:
        """The real port assigned to a virtual (host, port); allocates
        on first use, returns the recorded one after."""
        key = (vhost, vport, proto)
        with self._lock:
            real = self._fwd.get(key)
            if real is None:
                real = self.alloc.next_port(proto)
                self._fwd[key] = real
                self._rev[(real, proto)] = (vhost, vport)
            return real

    def rebind(self, vhost: int, vport: int, proto: int) -> int:
        """Replace a stale assignment (probe-to-bind race lost): drop
        the recorded real port and allocate a fresh one."""
        key = (vhost, vport, proto)
        with self._lock:
            old = self._fwd.pop(key, None)
            if old is not None:
                self._rev.pop((old, proto), None)
        return self.real_port(vhost, vport, proto)

    def register_eph(self, vhost: int, vport: int, proto: int,
                     real: int) -> None:
        """Record a kernel-assigned ephemeral real port under its
        virtual identity (so peers resolve it in recvfrom)."""
        with self._lock:
            self._fwd[(vhost, vport, proto)] = real
            self._rev[(real, proto)] = (vhost, vport)

    def virtual_of(self, real: int, proto: int):
        """(vhost, vport) of a real port, or None if unregistered."""
        with self._lock:
            return self._rev.get((real, proto))

    def wait_for(self, vhost: int, vport: int, proto: int,
                 timeout: float = 5.0):
        """Block until (vhost, vport) has a real assignment — the
        analog of SYN retransmission riding out a not-yet-listening
        server. Returns the real port, or None on timeout."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                real = self._fwd.get((vhost, vport, proto))
            if real is not None:
                return real
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.005)


class HostTimer:
    """timerfd stand-in built from a socketpair + threading.Timer
    (os.timerfd_create only exists from Python 3.13; this runs
    anywhere). The read end is a real fd — epoll/select/poll see it —
    and each expiration feeds one 8-byte count, so a blocking read
    returns the expirations since the last read, like timerfd(2).

    `time_scale` converts virtual nanoseconds to real seconds (the
    same factor the executor applies to sleep), so a 1 s virtual
    timer fires after time_scale real seconds.
    """

    def __init__(self, time_scale: float):
        self.time_scale = time_scale
        self._r, self._w = socket.socketpair()
        self._r.setblocking(True)
        self._timer: threading.Timer | None = None
        self._lock = threading.Lock()
        self._interval_ns = 0
        self._closed = False

    def fileno(self) -> int:
        return self._r.fileno()

    def _fire(self):
        with self._lock:
            if self._closed:
                return
            try:
                self._w.send(struct.pack("<Q", 1))
            except OSError:
                return
            if self._interval_ns > 0:
                self._timer = threading.Timer(
                    self._interval_ns * self.time_scale / 1e9, self._fire)
                self._timer.daemon = True
                self._timer.start()

    def _drain(self) -> int:
        """Nonblocking: consume and sum queued expiration counts."""
        total = 0
        self._r.setblocking(False)
        try:
            while True:
                try:
                    chunk = self._r.recv(8)
                except BlockingIOError:
                    break
                if not chunk:
                    break
                total += struct.unpack("<Q", chunk.ljust(8, b"\0"))[0]
        finally:
            self._r.setblocking(True)
        return total

    def settime(self, expire_ns: int, interval_ns: int = 0) -> int:
        """Arm (relative expire + optional interval, timerfd(2)
        default semantics) or disarm with expire_ns == 0. Disarm also
        discards not-yet-read expirations, matching the simulated
        timer_disarm invalidating in-flight fires."""
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._interval_ns = int(interval_ns)
            if expire_ns == 0:
                self._drain()
                return 0
            self._timer = threading.Timer(
                int(expire_ns) * self.time_scale / 1e9, self._fire)
            self._timer.daemon = True
            self._timer.start()
            return 0

    def read_blocking(self) -> int:
        """Block until >=1 expiration, return the count since the last
        read (the timerfd read contract)."""
        chunk = self._r.recv(8)
        if not chunk:
            return 0
        total = struct.unpack("<Q", chunk.ljust(8, b"\0"))[0]
        return total + self._drain()

    def close(self):
        with self._lock:
            self._closed = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
        for s in (self._r, self._w):
            try:
                s.close()
            except OSError:
                pass


def pipe_pair():
    """A real unidirectional pipe: (read_fd, write_fd) raw fds."""
    return os.pipe()
