"""Real-host-kernel backend: run vproc generator programs against the
actual OS.

This is the conformance half of the reference's dual-mode testing
discipline (SURVEY §4, src/test/test_launcher.c): every workload the
repo runs in-sim can also execute here, unchanged, with each virtual
process driven by a real OS thread making real syscalls — real
sockets/epoll/pipes on localhost, real blocking. Programs see the
SAME virtual namespace the simulation presents (simulated IP ints,
program-chosen port numbers, vproc fd-base layout); the mapping to
real resources happens inside this executor (hostrun/kernel.py), so
the two backends' traces line up without heavyweight rewriting
(docs/7-conformance.md).

Backend-independent syscalls (files, deterministic random, pids,
signals, fork/exec stubs) dispatch through the SAME SHARED_OPS table
the simulation uses (process/vproc.py) — identical by construction.

Known deviations from the simulated backend (see the docs matrix):
- gettime reports scaled wall time: real durations, not exact
  simulated instants (traces normalize clocks away);
- getsockopt(SO_SNDBUF/RCVBUF) returns the user-set value, masking
  Linux's doubling, to match the reference's emulated getsockopt;
- sleep-granularity asserts (test_sleep's exact-delta check) cannot
  hold on a real clock — that workload is sim-only.
"""

from __future__ import annotations

import errno as _errno
import os
import select
import socket as _socket
import threading
import time

from shadow_tpu.net.sockets import MIN_RANDOM_PORT
from shadow_tpu.net.state import SocketType
from shadow_tpu.process.vproc import (
    EPOLL, EPOLL_FD_BASE, FILE_FD_BASE, PIPE_FD_BASE, SHARED_OPS,
    TIMER_FD_BASE, HostSideState, Sys, file_read, file_write,
    stdio_write)

from .kernel import HostTimer, PortAllocator, PortMap

_READ_CAP = 1 << 20     # cap a single real read/recv chunk


class _ProcKilled(BaseException):
    """Unhandled-signal self-delivery: unwinds the driving thread out
    of the generator (the slave_incrementPluginError analog)."""

    def __init__(self, sig):
        self.sig = sig


class _HProc:
    """One virtual process = one OS thread driving its generator.
    Duck-types the fields SHARED_OPS and _deliver_signal touch on the
    simulation's _Proc."""

    def __init__(self, host, gen, pid, start_time=0, stop_time=-1):
        self.host = host
        self.gen = gen
        self.pid = pid
        self.start_time = start_time
        self.stop_time = stop_time
        self.sig_handlers = {}
        self.last_errno = 0
        self.done = False
        self.result = None
        self.killed = None           # signal number once killed
        self.epolls = {}             # vfd -> entry (per-proc, like sim)
        self.next_epfd = EPOLL_FD_BASE
        self.finished = threading.Event()
        self.thread = None
        self.error = None


class _HMutex:
    def __init__(self):
        self.lock = threading.Lock()
        self.owner = 0
        self.meta = threading.Lock()


class _HCond:
    def __init__(self):
        self.waiters = {}            # pid -> Event, insertion = FIFO
        self.meta = threading.Lock()


class HostKernelExecutor:
    """ProcessRuntime's API shape (spawn/run/stdio_of) against the
    real kernel. `time_scale` maps simulated nanoseconds to real
    seconds for sleeps/timers/start-times (default: 1 sim second =
    50 real milliseconds)."""

    def __init__(self, bundle, time_scale: float = 0.05, trace=None,
                 portmap: PortMap | None = None):
        self.bundle = bundle
        self.cfg = bundle.cfg
        self.time_scale = float(time_scale)
        self.trace = trace
        self.portmap = portmap or PortMap(
            PortAllocator(seed=int(self.cfg.seed)))
        # identical host-side state to the simulation's: same seed ->
        # same getrandom/c_rand streams, same virtual files
        self.host_state = HostSideState(
            seed=int(self.cfg.seed), host_names=list(bundle.host_names))
        self.procs: list[_HProc] = []
        self.errors: list = []
        self._fds: dict[tuple, dict] = {}       # (host, vfd) -> entry
        self._next_sock: dict[int, int] = {}
        self._next_pipe: dict[int, int] = {}
        self._timer_alloc: dict[int, int] = {}
        self._next_eph: dict[int, int] = {}
        self._mutexes: dict[tuple, _HMutex] = {}
        self._next_mutex: dict[int, int] = {}
        self._conds: dict[tuple, _HCond] = {}
        self._next_cond: dict[int, int] = {}
        self._next_pid = 1
        self._bound: dict[tuple, int] = {}      # (real, proto) -> refs
        self._lock = threading.Lock()
        self._t0 = None
        # simulated-IP -> host index (programs address peers by the
        # sim IPs env['resolve']/gethostbyname hand them)
        self._ip_host = {int(bundle.ip_of(n)): i
                         for i, n in enumerate(bundle.host_names)}
        self._host_ip = {i: ip for ip, i in self._ip_host.items()}

    # -- registration ---------------------------------------------------

    def spawn(self, host: int, proc_fn, start_time: int = 0,
              stop_time: int = -1):
        gen = proc_fn(host)
        if not hasattr(gen, "send") or not hasattr(gen, "close"):
            raise TypeError(
                f"virtual process for host {host} returned "
                f"{type(gen).__name__}, not a generator")
        with self._lock:
            pid = self._next_pid
            self._next_pid += 1
        p = _HProc(host, gen, pid, start_time, stop_time)
        self.procs.append(p)
        return p

    def stdio_of(self, host: int, pid: int, fd: int = 1) -> bytes:
        return bytes(self.host_state.stdio.get((host, pid, fd), b""))

    # -- run loop -------------------------------------------------------

    def _scale(self, ns: int) -> float:
        return max(ns, 0) * self.time_scale / 1e9

    def run(self, wall_timeout: float | None = None):
        """Start every process thread, wait for completion, tear down
        real resources. Raises the first program error (assertion
        failures surface exactly like sim-side plugin errors)."""
        if wall_timeout is None:
            wall_timeout = self._scale(int(self.cfg.end_time)) + 30.0
        self._t0 = time.monotonic()
        for p in list(self.procs):
            self._start(p)
        deadline = time.monotonic() + wall_timeout
        stuck = []
        for p in self.procs:        # list may grow via thread_create
            remaining = max(deadline - time.monotonic(), 0.0)
            p.finished.wait(remaining)
            if not p.finished.is_set():
                stuck.append(p)
        if stuck:
            for p in self.procs:
                p.killed = p.killed or -1
            self._teardown()        # closing fds unblocks real syscalls
            for p in stuck:
                p.finished.wait(2.0)
            raise TimeoutError(
                "host-kernel run exceeded its wall budget "
                f"({wall_timeout:.1f}s); stuck: "
                f"{[(p.host, p.pid) for p in stuck]}")
        self._teardown()
        if self.errors:
            raise self.errors[0]

    def _start(self, p: _HProc):
        t = threading.Thread(target=self._drive, args=(p,), daemon=True,
                             name=f"hostrun-h{p.host}-p{p.pid}")
        p.thread = t
        t.start()

    def _drive(self, p: _HProc):
        try:
            if p.start_time > 0:
                time.sleep(self._scale(p.start_time))
            if p.stop_time >= 0:
                killer = threading.Timer(
                    self._scale(p.stop_time - p.start_time),
                    lambda: setattr(p, "killed", p.killed or -1))
                killer.daemon = True
                killer.start()
            try:
                call = next(p.gen)
                while True:
                    if p.killed is not None:
                        p.gen.close()
                        if self.trace is not None:
                            self.trace.record_exit(
                                p.host, p.pid, ("killed", p.killed))
                        return
                    result = self._exec(p, call)
                    if self.trace is not None:
                        self.trace.record(p.host, p.pid, call.op,
                                          call.args, result)
                    call = p.gen.send(result)
            except StopIteration as e:
                p.result = e.value
                if self.trace is not None:
                    self.trace.record_exit(p.host, p.pid, p.result)
        except _ProcKilled as k:
            p.killed = k.sig
            if self.trace is not None:
                self.trace.record_exit(p.host, p.pid, ("killed", k.sig))
        except BaseException as e:          # noqa: BLE001 — reported by run()
            p.error = e
            self.errors.append(e)
        finally:
            p.done = True
            p.finished.set()

    def _teardown(self):
        for key, ent in list(self._fds.items()):
            self._close_entry(ent)
        self._fds.clear()
        for p in self.procs:
            for ent in p.epolls.values():
                self._close_entry(ent)

    @staticmethod
    def _close_entry(ent):
        try:
            k = ent["kind"]
            if k == "sock":
                ent["sock"].close()
            elif k == "ep":
                ent["ep"].close()
            elif k == "timer":
                ent["t"].close()
            elif k == "chan":
                for fd in (ent.get("r"), ent.get("w")):
                    if fd is not None:
                        try:
                            os.close(fd)
                        except OSError:
                            pass
                for o in ent.get("objs", ()):
                    o.close()
        except (OSError, KeyError):
            pass

    # -- lookup helpers -------------------------------------------------

    def _entry(self, p: _HProc, vfd: int):
        if EPOLL_FD_BASE <= vfd < PIPE_FD_BASE:
            return p.epolls.get(vfd)
        return self._fds.get((p.host, vfd))

    def _realfd(self, p: _HProc, vfd: int):
        ent = self._entry(p, vfd)
        if ent is None:
            return None
        k = ent["kind"]
        if k == "sock":
            return ent["sock"].fileno()
        if k == "timer":
            return ent["t"].fileno()
        if k == "chan":
            return ent["r"] if ent.get("r") is not None else ent["w"]
        if k == "ep":
            return ent["ep"].fileno()
        return None

    def _host_of_ip(self, ip: int, default: int) -> int:
        if (ip >> 24) == 127:
            return default
        return self._ip_host.get(int(ip), default)

    def _deliver_signal(self, p: _HProc, sig: int) -> int:
        """SHARED_OPS hook: same contract as the simulation's. The
        handler runs synchronously on the calling thread; an unhandled
        signal kills the target (self-delivery unwinds immediately,
        cross-thread targets die at their next syscall boundary)."""
        handler = p.sig_handlers.get(sig)
        if handler is None:
            p.killed = sig
            cur = threading.current_thread()
            if p.thread is cur or p.thread is None:
                raise _ProcKilled(sig)
            return -1
        handler(sig)
        return 0

    # -- syscall execution ---------------------------------------------

    def _exec(self, p: _HProc, call: Sys):
        op, a = call.op, call.args
        h = p.host

        if op in SHARED_OPS:
            ready, result = SHARED_OPS[op](self.host_state, self, p, a)
            return result

        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            raise ValueError(f"host-kernel backend: unsupported op {op}")
        return fn(p, h, a)

    # sockets ----------------------------------------------------------

    @staticmethod
    def _proto(stype):
        return (_socket.SOCK_STREAM if stype == SocketType.TCP
                else _socket.SOCK_DGRAM)

    def _op_socket(self, p, h, a):
        proto = self._proto(a[0])
        try:
            s = _socket.socket(_socket.AF_INET, proto)
        except OSError:
            return -1
        s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 0)
        with self._lock:
            vfd = self._next_sock.get(h, 0)
            self._next_sock[h] = vfd + 1
            self._fds[(h, vfd)] = {
                "kind": "sock", "sock": s, "proto": proto,
                "vbound": None, "user_buf": {}}
        return vfd

    def _op_bind(self, p, h, a):
        vfd, vport = a
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "sock":
            return -1
        proto = ent["proto"]
        if vport == 0:
            try:
                ent["sock"].bind(("127.0.0.1", 0))
            except OSError:
                return -1
            real = ent["sock"].getsockname()[1]
            with self._lock:
                veph = self._next_eph.get(h, MIN_RANDOM_PORT)
                self._next_eph[h] = veph + 1
            self.portmap.register_eph(h, veph, proto, real)
            self._track_bound(real, proto, +1)
            ent["vbound"] = veph
            return veph
        real = self.portmap.real_port(h, vport, proto)
        for attempt in range(4):
            try:
                ent["sock"].bind(("127.0.0.1", real))
                self._track_bound(real, proto, +1)
                ent["vbound"] = vport
                ent["real_port"] = real
                return vport
            except OSError as e:
                if e.errno != _errno.EADDRINUSE:
                    return -1        # EINVAL (re-bind of a bound socket)
                if (real, proto) in self._bound:
                    return -1        # OUR conflict: virtual EADDRINUSE
                # an outside process squats our sticky port — re-map
                # deterministically and retry (collision retry contract)
                real = self.portmap.rebind(h, vport, proto)
        return -1

    def _track_bound(self, real, proto, delta):
        with self._lock:
            key = (real, proto)
            n = self._bound.get(key, 0) + delta
            if n > 0:
                self._bound[key] = n
            else:
                self._bound.pop(key, None)

    def _op_listen(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return -1
        ent["sock"].listen(64)
        return 0

    def _op_connect(self, p, h, a):
        vfd, ip, vport = a
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "sock":
            return -1
        dst = self._host_of_ip(ip, h)
        # the analog of SYN retransmission riding out a server that
        # has not bound yet — but a never-bound port is a fast RST
        real = self.portmap.wait_for(dst, vport, ent["proto"],
                                     timeout=self._scale(
                                         int(self.cfg.end_time)) + 1.0)
        if real is None:
            return -1
        try:
            ent["sock"].connect(("127.0.0.1", real))
        except OSError:
            return -1
        if ent["vbound"] is None:
            self._register_autobound(h, ent)
        return 0

    def _register_autobound(self, h, ent):
        """Record a kernel-autobound local port under a virtual
        ephemeral identity so peers can resolve it."""
        try:
            real = ent["sock"].getsockname()[1]
        except OSError:
            return
        with self._lock:
            veph = self._next_eph.get(h, MIN_RANDOM_PORT)
            self._next_eph[h] = veph + 1
        self.portmap.register_eph(h, veph, ent["proto"], real)
        ent["vbound"] = veph

    def _op_accept(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return -1
        try:
            conn, _addr = ent["sock"].accept()
        except OSError:
            return -1
        with self._lock:
            vfd = self._next_sock.get(h, 0)
            self._next_sock[h] = vfd + 1
            self._fds[(h, vfd)] = {
                "kind": "sock", "sock": conn, "proto": ent["proto"],
                "vbound": None, "user_buf": {}}
        return vfd

    def _op_send(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return -1
        try:
            return ent["sock"].send(b"\0" * int(a[1]))
        except OSError:
            return -1

    def _op_send_data(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return -1
        try:
            return ent["sock"].send(bytes(a[1]))
        except OSError:
            return -1

    def _op_recv(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return 0
        try:
            data = ent["sock"].recv(min(int(a[1]), _READ_CAP))
        except OSError:
            return 0
        return len(data)

    def _op_recv_data(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return b""
        try:
            return ent["sock"].recv(min(int(a[1]), _READ_CAP))
        except OSError:
            return b""

    def _dst_addr(self, p, h, ent, ip, vport):
        dst = self._host_of_ip(ip, h)
        real = self.portmap.wait_for(dst, vport, ent["proto"],
                                     timeout=2.0)
        return ("127.0.0.1", real) if real is not None else None

    def _op_sendto(self, p, h, a):
        vfd, ip, vport, n = a
        return self._sendto_impl(p, h, vfd, ip, vport, b"\0" * int(n))

    def _op_sendto_data(self, p, h, a):
        vfd, ip, vport, data = a
        return self._sendto_impl(p, h, vfd, ip, vport, bytes(data))

    def _sendto_impl(self, p, h, vfd, ip, vport, payload):
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "sock":
            return False
        if ent["vbound"] is None:
            # a sendto on an unbound UDP socket autobinds — register
            # the identity so the receiver's recvfrom resolves us
            try:
                ent["sock"].bind(("127.0.0.1", 0))
            except OSError:
                return False
            self._register_autobound(h, ent)
        addr = self._dst_addr(p, h, ent, ip, vport)
        if addr is None:
            return False
        try:
            ent["sock"].sendto(payload, addr)
        except OSError:
            return False
        return True

    def _op_recvfrom(self, p, h, a):
        ip, vport, data = self._recvfrom_impl(p, h, a[0])
        return (ip, vport, len(data))

    def _op_recvfrom_data(self, p, h, a):
        return self._recvfrom_impl(p, h, a[0])

    def _recvfrom_impl(self, p, h, vfd):
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "sock":
            return (-1, -1, b"")
        data, addr = ent["sock"].recvfrom(65536)
        virt = self.portmap.virtual_of(addr[1], ent["proto"])
        if virt is None:
            return (self._host_ip.get(h, -1), -1, data)
        src_host, src_vport = virt
        return (self._host_ip.get(src_host, -1), src_vport, data)

    def _op_shutdown(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "sock":
            return 0
        try:
            ent["sock"].shutdown(int(a[1]))   # SHUT_* ints match
        except OSError:
            pass
        return 0

    def _op_setsockopt(self, p, h, a):
        vfd, opt, val = a
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "sock":
            return -1
        if opt not in (_socket.SO_SNDBUF, _socket.SO_RCVBUF):
            return -1
        ent["sock"].setsockopt(_socket.SOL_SOCKET, opt, int(val))
        # report back the USER value: Linux doubles the stored size
        # for bookkeeping, but the emulated surface (and the
        # reference's sockbuf test) expects the set value round-trip
        ent["user_buf"][opt] = int(val)
        return 0

    def _op_getsockopt(self, p, h, a):
        vfd, opt = a
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "sock":
            return -1
        if opt not in (_socket.SO_SNDBUF, _socket.SO_RCVBUF):
            return -1
        if opt in ent["user_buf"]:
            return ent["user_buf"][opt]
        return ent["sock"].getsockopt(_socket.SOL_SOCKET, opt)

    def _op_ioctl_inq(self, p, h, a):
        import fcntl
        import struct
        import termios

        fd = self._realfd(p, a[0])
        if fd is None:
            return -1
        buf = fcntl.ioctl(fd, termios.FIONREAD, struct.pack("i", 0))
        return struct.unpack("i", buf)[0]

    def _op_ioctl_outq(self, p, h, a):
        import fcntl
        import struct
        import termios

        fd = self._realfd(p, a[0])
        if fd is None:
            return -1
        buf = fcntl.ioctl(fd, termios.TIOCOUTQ, struct.pack("i", 0))
        return struct.unpack("i", buf)[0]

    # time -------------------------------------------------------------

    def _op_gettime(self, p, h, a):
        return int((time.monotonic() - self._t0) / self.time_scale * 1e9)

    def _op_sleep(self, p, h, a):
        time.sleep(self._scale(int(a[0])))
        return 0

    def _op_gethostbyname(self, p, h, a):
        addr = self.bundle.dns.resolve_name(a[0])
        return addr.ip if addr is not None else -1

    # timers -----------------------------------------------------------

    def _op_timerfd_create(self, p, h, a):
        with self._lock:
            nxt = self._timer_alloc.get(h, 0)
            if nxt >= self.cfg.timers_per_host:
                return -1
            self._timer_alloc[h] = nxt + 1
            vfd = TIMER_FD_BASE + nxt
            self._fds[(h, vfd)] = {"kind": "timer",
                                   "t": HostTimer(self.time_scale)}
        return vfd

    def _op_timerfd_settime(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "timer":
            return -1
        return ent["t"].settime(int(a[1]), int(a[2]))

    def _op_timerfd_read(self, p, h, a):
        ent = self._entry(p, a[0])
        if ent is None or ent["kind"] != "timer":
            return -1
        return ent["t"].read_blocking()

    # readiness --------------------------------------------------------

    def _op_wait_readable(self, p, h, a):
        pairs = [(vfd, self._realfd(p, vfd)) for vfd in a[0]]
        reals = [r for _, r in pairs if r is not None]
        rl, _, _ = select.select(reals, [], [])
        ready = set(rl)
        return [vfd for vfd, r in pairs if r in ready]

    def _sel_timeout(self, timeout_ns):
        return None if timeout_ns < 0 else self._scale(int(timeout_ns))

    def _op_poll(self, p, h, a):
        entries, timeout_ns = a
        rmap = {vfd: self._realfd(p, vfd) for vfd, _ in entries}
        rfds = [rmap[v] for v, e in entries
                if e & EPOLL.IN and rmap[v] is not None]
        wfds = [rmap[v] for v, e in entries
                if e & EPOLL.OUT and rmap[v] is not None]
        rl, wl, _ = select.select(rfds, wfds, [],
                                  self._sel_timeout(timeout_ns))
        rl, wl = set(rl), set(wl)
        out = []
        for vfd, ev in entries:
            rev = ((EPOLL.IN if rmap[vfd] in rl else 0)
                   | (EPOLL.OUT if rmap[vfd] in wl else 0)) & ev
            if rev:
                out.append((vfd, rev))
        return out

    def _op_select(self, p, h, a):
        rfds, wfds, timeout_ns = a
        rmap = {v: self._realfd(p, v) for v in tuple(rfds) + tuple(wfds)}
        rl, wl, _ = select.select(
            [rmap[v] for v in rfds if rmap[v] is not None],
            [rmap[v] for v in wfds if rmap[v] is not None], [],
            self._sel_timeout(timeout_ns))
        rl, wl = set(rl), set(wl)
        return ([v for v in rfds if rmap[v] in rl],
                [v for v in wfds if rmap[v] in wl])

    # epoll ------------------------------------------------------------

    @staticmethod
    def _ep_events(v_events: int) -> int:
        ev = 0
        if v_events & EPOLL.IN:
            ev |= select.EPOLLIN
        if v_events & EPOLL.OUT:
            ev |= select.EPOLLOUT
        if v_events & EPOLL.ET:
            ev |= select.EPOLLET
        if v_events & EPOLL.ONESHOT:
            ev |= select.EPOLLONESHOT
        return ev

    def _op_epoll_create(self, p, h, a):
        vfd = p.next_epfd
        p.next_epfd += 1
        p.epolls[vfd] = {"kind": "ep", "ep": select.epoll(), "vfds": {}}
        return vfd

    def _op_epoll_ctl(self, p, h, a):
        epfd, ctl, vfd, events = a
        ent = p.epolls.get(epfd)
        if ent is None:
            return -1
        real = self._realfd(p, vfd)
        if real is None:
            return -1
        try:
            if ctl == EPOLL.CTL_ADD:
                ent["ep"].register(real, self._ep_events(events))
                ent["vfds"][real] = vfd
            elif ctl == EPOLL.CTL_MOD:
                ent["ep"].modify(real, self._ep_events(events))
                ent["vfds"][real] = vfd
            elif ctl == EPOLL.CTL_DEL:
                ent["ep"].unregister(real)
                ent["vfds"].pop(real, None)
            else:
                return -1
        except (OSError, FileExistsError, FileNotFoundError):
            return -1               # EEXIST / ENOENT, like the sim
        return 0

    def _op_epoll_wait(self, p, h, a):
        ent = p.epolls.get(a[0])
        if ent is None:
            return []
        evs = ent["ep"].poll()      # blocks, like the vproc contract
        out = []
        for real, ev in evs:
            vfd = ent["vfds"].get(real)
            if vfd is None:
                continue
            mask = 0
            if ev & (select.EPOLLIN | select.EPOLLHUP | select.EPOLLERR):
                mask |= EPOLL.IN
            if ev & select.EPOLLOUT:
                mask |= EPOLL.OUT
            if mask:
                out.append((vfd, mask))
        return out

    # channels / files / stdio -----------------------------------------

    def _op_pipe(self, p, h, a):
        r, w = os.pipe()
        with self._lock:
            base = self._next_pipe.get(h, PIPE_FD_BASE)
            self._next_pipe[h] = base + 2
            self._fds[(h, base)] = {"kind": "chan", "r": r, "w": None}
            self._fds[(h, base + 1)] = {"kind": "chan", "r": None, "w": w}
        return (base, base + 1)

    def _op_socketpair(self, p, h, a):
        s1, s2 = _socket.socketpair()
        with self._lock:
            base = self._next_pipe.get(h, PIPE_FD_BASE)
            self._next_pipe[h] = base + 2
            self._fds[(h, base)] = {
                "kind": "chan", "r": s1.fileno(), "w": s1.fileno(),
                "objs": (s1,)}
            self._fds[(h, base + 1)] = {
                "kind": "chan", "r": s2.fileno(), "w": s2.fileno(),
                "objs": (s2,)}
        return (base, base + 1)

    def _op_write(self, p, h, a):
        vfd, data = a
        if vfd in (1, 2):
            return stdio_write(self.host_state,
                               self.bundle.host_names[h], h, p.pid,
                               vfd, bytes(data))
        if FILE_FD_BASE <= vfd < TIMER_FD_BASE:
            return file_write(self.host_state, h, vfd, bytes(data))
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "chan" or ent.get("w") is None:
            return -1
        try:
            return os.write(ent["w"], bytes(data))
        except BrokenPipeError:
            return -1               # EPIPE: read side closed
        except OSError:
            return -1

    def _op_read(self, p, h, a):
        vfd, maxb = a
        if FILE_FD_BASE <= vfd < TIMER_FD_BASE:
            return file_read(self.host_state, h, vfd, int(maxb))
        ent = self._entry(p, vfd)
        if ent is None or ent["kind"] != "chan" or ent.get("r") is None:
            return b""
        try:
            return os.read(ent["r"], min(int(maxb), _READ_CAP))
        except OSError:
            return b""

    def _op_close(self, p, h, a):
        vfd = a[0]
        if FILE_FD_BASE <= vfd < TIMER_FD_BASE:
            return (0 if self.host_state.file_fds.pop((h, vfd), None)
                    is not None else -1)
        if EPOLL_FD_BASE <= vfd < PIPE_FD_BASE:
            ent = p.epolls.pop(vfd, None)
            if ent is not None:
                ent["ep"].close()
            return 0
        with self._lock:
            ent = self._fds.pop((h, vfd), None)
        if ent is None:
            return 0
        if ent["kind"] == "sock" and ent.get("real_port") is not None:
            self._track_bound(ent["real_port"], ent["proto"], -1)
        self._close_entry(ent)
        return 0

    # threads / sync ---------------------------------------------------

    def _op_thread_create(self, p, h, a):
        gen = a[0](h)
        with self._lock:
            pid = self._next_pid
            self._next_pid += 1
        t = _HProc(h, gen, pid, start_time=0)
        self.procs.append(t)
        self._start(t)
        return t.pid

    def _op_thread_join(self, p, h, a):
        tgt = next((q for q in self.procs
                    if q.pid == a[0] and q.host == h), None)
        if tgt is None:
            return None
        tgt.finished.wait()
        return tgt.result

    def _op_mutex_init(self, p, h, a):
        with self._lock:
            mid = self._next_mutex.get(h, 1)
            self._next_mutex[h] = mid + 1
            self._mutexes[(h, mid)] = _HMutex()
        return mid

    def _op_mutex_lock(self, p, h, a):
        m = self._mutexes.get((h, a[0]))
        if m is None:
            return -1
        with m.meta:
            if m.owner == p.pid:
                return 0            # sim semantics: re-lock by owner
        m.lock.acquire()
        with m.meta:
            m.owner = p.pid
        return 0

    def _op_mutex_trylock(self, p, h, a):
        m = self._mutexes.get((h, a[0]))
        if m is None:
            return -1
        with m.meta:
            if m.owner == p.pid:
                return True
            if m.owner:
                return False        # EBUSY
            if not m.lock.acquire(blocking=False):
                return False
            m.owner = p.pid
            return True

    def _op_mutex_unlock(self, p, h, a):
        m = self._mutexes.get((h, a[0]))
        if m is None:
            return -1
        with m.meta:
            if m.owner != p.pid:
                return -1           # EPERM
            m.owner = 0
        m.lock.release()
        return 0

    def _op_cond_init(self, p, h, a):
        with self._lock:
            cid = self._next_cond.get(h, 1)
            self._next_cond[h] = cid + 1
            self._conds[(h, cid)] = _HCond()
        return cid

    def _op_cond_wait(self, p, h, a):
        cid, mid = a
        c = self._conds.get((h, cid))
        m = self._mutexes.get((h, mid))
        if c is None or m is None:
            return -1
        with m.meta:
            if m.owner != p.pid:
                return -1           # EPERM: must hold the mutex
        ev = threading.Event()
        with c.meta:
            c.waiters[p.pid] = ev
        self._op_mutex_unlock(p, h, (mid,))
        ev.wait()
        self._op_mutex_lock(p, h, (mid,))
        with c.meta:
            c.waiters.pop(p.pid, None)
        return 0

    def _op_cond_signal(self, p, h, a):
        c = self._conds.get((h, a[0]))
        if c is None:
            return -1
        with c.meta:
            for pid, ev in c.waiters.items():   # FIFO: oldest waiter
                if not ev.is_set():
                    ev.set()
                    break
        return 0

    def _op_cond_broadcast(self, p, h, a):
        c = self._conds.get((h, a[0]))
        if c is None:
            return -1
        with c.meta:
            for ev in c.waiters.values():
                ev.set()
        return 0
