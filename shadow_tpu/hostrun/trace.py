"""Syscall trace recording + normalization for dual-mode conformance.

Both backends — the vproc simulation (process/vproc.py) and the
real-kernel executor (hostrun/executor.py) — attach the same
TraceRecorder: every COMPLETED syscall appends one raw record
(host, pid, op, args, ret), plus one exit record per process. The
normalizer then rewrites each per-process sequence into a
backend-independent canonical form the differential checker
(hostrun/diff.py) can compare exactly:

- fds -> kind-prefixed first-appearance tokens per process ("sock0",
  "pipe1", ...), retired on close so slot reuse vs fresh numbering
  cannot diverge the rename
- payload bytes -> (length, sha256-prefix) digests
- wall/sim clocks -> "T" (gettime is timing, not semantics)
- kernel-chosen ephemeral ports -> "P"
- queue depths (SIOCINQ/OUTQ) and timer expiration counts -> sign
  tokens ("+"), since both are legitimately timing-dependent
- ready-set results (epoll_wait/poll/select/wait_readable) sorted,
  and consecutive identical ready-sets separated only by stream ops
  folded to one — a wakeup-granularity difference, not a semantic one
- consecutive same-fd stream ops (send/recv/read/write families)
  coalesced into one record with summed counts / concatenated
  payload digests — partial-transfer chunking differs per backend

What stays raw is the point of the exercise: op order, success/-1
returns, port numbers programs chose, byte totals, payload content,
mutex/cond ids, pids. See docs/7-conformance.md for the full matrix.
"""

from __future__ import annotations

import hashlib
import json
import threading

from shadow_tpu.process.vproc import (
    EPOLL_FD_BASE, FILE_FD_BASE, PIPE_FD_BASE, TIMER_FD_BASE)

# ops whose consecutive same-fd records coalesce (partial-transfer
# chunking is backend timing, the TOTAL is the semantics)
STREAM_OPS = frozenset((
    "send", "send_data", "recv", "recv_data", "write", "read"))
# ops returning a ready-set (order-insensitive; foldable)
READY_OPS = frozenset(("epoll_wait", "poll", "select", "wait_readable"))


def _digest(data: bytes):
    return [len(data), hashlib.sha256(bytes(data)).hexdigest()[:12]]


def _jsonable(v):
    """Best-effort canonical value for arbitrary process results."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (bytes, bytearray)):
        return _digest(v)
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return repr(v)


class TraceRecorder:
    """Thread-safe raw-record sink shared by one backend run.

    `ip_names` maps simulated IP ints to host names so addresses
    normalize to stable identities (both backends hand programs the
    same simulated IPs, so this is cosmetic-but-readable).
    """

    def __init__(self, ip_names=None):
        self.ip_names = dict(ip_names or {})
        self._records: list[tuple] = []
        self._lock = threading.Lock()

    # -- recording (hot path: raw append only) -------------------------

    def record(self, host: int, pid: int, op: str, args: tuple, ret):
        with self._lock:
            self._records.append((host, pid, op, args, ret))

    def record_exit(self, host: int, pid: int, result):
        with self._lock:
            self._records.append((host, pid, "_exit", (), result))

    # -- normalization --------------------------------------------------

    def _ip(self, ip):
        if not isinstance(ip, int):
            return _jsonable(ip)
        if (ip >> 24) == 127:
            return "loopback"
        return self.ip_names.get(ip, ip)

    def normalized(self) -> dict:
        """{'h<host>:p<pid>': [canonical records...]} — the form the
        differential checker compares."""
        with self._lock:
            records = list(self._records)
        seqs: dict[tuple, list] = {}
        for host, pid, op, args, ret in records:
            seqs.setdefault((host, pid), []).append((op, args, ret))
        out = {}
        for (host, pid), seq in sorted(seqs.items()):
            seq = _fold_ready_sets(seq)
            seq = _coalesce_streams(seq)
            out[f"h{host}:p{pid}"] = _Canonicalizer(self._ip).run(seq)
        return out

    def dump(self, path: str, meta=None) -> None:
        doc = {"meta": meta or {}, "procs": self.normalized()}
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)


def load(path: str) -> dict:
    """Load a dumped trace; returns the full {'meta', 'procs'} doc."""
    with open(path) as f:
        return json.load(f)


# ---------------------------------------------------------------------
# raw-sequence passes (run BEFORE canonicalization so payload bytes
# are still concatenable and fds still raw-comparable)
# ---------------------------------------------------------------------

def _fold_ready_sets(seq):
    """Drop a ready-set record identical to the previous kept one when
    only stream ops sit between them: a server looping
    epoll_wait -> send with backend-specific partial-send chunking
    produces N vs M wakeups for the same semantics."""
    out = []
    last_ready = None          # index into out of last kept ready rec
    streams_only = True
    for rec in seq:
        op = rec[0]
        if op in READY_OPS:
            if (last_ready is not None and streams_only
                    and out[last_ready] == rec):
                continue
            out.append(rec)
            last_ready = len(out) - 1
            streams_only = True
            continue
        if op not in STREAM_OPS:
            last_ready = None
        out.append(rec)
    return out


def _merge(a, b):
    """Merge two same-op same-fd stream records (None = can't)."""
    op, args_a, ret_a = a
    _, args_b, ret_b = b
    if args_a[0] != args_b[0]:
        return None
    fd = args_a[0]
    if op in ("send", "recv"):
        if not (isinstance(ret_a, int) and isinstance(ret_b, int)
                and ret_a >= 0 and ret_b >= 0):
            return None
        return (op, (fd,), ret_a + ret_b)
    if op in ("send_data", "write"):
        if not (isinstance(ret_a, int) and isinstance(ret_b, int)
                and ret_a >= 0 and ret_b >= 0):
            return None
        data = bytes(args_a[1]) + bytes(args_b[1])
        return (op, (fd, data), ret_a + ret_b)
    if op in ("recv_data", "read"):
        if not (isinstance(ret_a, (bytes, bytearray))
                and isinstance(ret_b, (bytes, bytearray))):
            return None
        return (op, (fd,), bytes(ret_a) + bytes(ret_b))
    return None


def _coalesce_streams(seq):
    out = []
    for rec in seq:
        op = rec[0]
        if out and op in STREAM_OPS and out[-1][0] == op:
            merged = _merge(out[-1], rec)
            if merged is not None:
                out[-1] = merged
                continue
        # normalize stream args up front so single records and merged
        # ones share a shape: (fd,) for count-carrying, (fd, data)
        # retained for send-side payloads
        if op in ("send", "recv", "recv_data", "read"):
            rec = (op, (rec[1][0],), rec[2])
        out.append(rec)
    return out


# ---------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------

def _fd_kind(fd: int) -> str:
    if fd >= TIMER_FD_BASE:
        return "timer"
    if fd >= FILE_FD_BASE:
        return "file"
    if fd >= PIPE_FD_BASE:
        return "pipe"
    if fd >= EPOLL_FD_BASE:
        return "ep"
    return "sock"


class _Canonicalizer:
    """Per-process canonical rewrite (fd tokens, digests, timing
    tokens). One instance per process sequence."""

    def __init__(self, ip_fn):
        self._ip = ip_fn
        self._tok: dict[int, str] = {}
        self._counts: dict[str, int] = {}

    def tok(self, fd):
        if not isinstance(fd, int) or fd < 0:
            return fd
        t = self._tok.get(fd)
        if t is None:
            kind = _fd_kind(fd)
            n = self._counts.get(kind, 0)
            self._counts[kind] = n + 1
            t = f"{kind}{n}"
            self._tok[fd] = t
        return t

    def retire(self, fd):
        self._tok.pop(fd, None)

    def run(self, seq):
        return [self.one(op, args, ret) for op, args, ret in seq]

    def one(self, op, a, ret):
        tok = self.tok
        if op == "socket":
            return [op, [int(a[0])], tok(ret)]
        if op == "bind":
            cret = ("P" if (a[1] == 0 and isinstance(ret, int)
                            and ret > 0) else ret)
            return [op, [tok(a[0]), int(a[1])], cret]
        if op in ("listen", "accept"):
            return [op, [tok(a[0])],
                    tok(ret) if op == "accept" else ret]
        if op == "connect":
            return [op, [tok(a[0]), self._ip(a[1]), int(a[2])], ret]
        if op in ("send", "recv"):
            return [op, [tok(a[0])], ret]
        if op == "send_data":
            return [op, [tok(a[0]), _digest(a[1])], ret]
        if op == "recv_data":
            cret = _digest(ret) if isinstance(ret, (bytes, bytearray)) \
                else ret
            return [op, [tok(a[0])], cret]
        if op == "sendto":
            return [op, [tok(a[0]), self._ip(a[1]), int(a[2]), a[3]],
                    _jsonable(ret)]
        if op == "sendto_data":
            return [op, [tok(a[0]), self._ip(a[1]), int(a[2]),
                         _digest(a[3])], _jsonable(ret)]
        if op in ("recvfrom", "recvfrom_data"):
            if isinstance(ret, tuple) and len(ret) == 3:
                payload = ret[2]
                cret = [self._ip(ret[0]), "P",
                        _digest(payload)
                        if isinstance(payload, (bytes, bytearray))
                        else payload]
            else:
                cret = _jsonable(ret)
            return [op, [tok(a[0])], cret]
        if op == "close":
            t = tok(a[0])
            self.retire(a[0])
            return [op, [t], ret]
        if op == "shutdown":
            return [op, [tok(a[0]), int(a[1])], ret]
        if op == "sleep":
            return [op, [int(a[0])], ret]
        if op == "gettime":
            return [op, [], "T"]
        if op == "gethostbyname":
            return [op, [a[0]], self._ip(ret) if ret != -1 else -1]
        if op == "timerfd_create":
            return [op, [], tok(ret)]
        if op == "timerfd_settime":
            return [op, [tok(a[0]), int(a[1]), int(a[2])], ret]
        if op == "timerfd_read":
            return [op, [tok(a[0])],
                    "+" if isinstance(ret, int) and ret > 0 else ret]
        if op in ("setsockopt", "getsockopt"):
            return [op, [tok(a[0])] + [int(x) for x in a[1:]], ret]
        if op in ("ioctl_inq", "ioctl_outq"):
            return [op, [tok(a[0])],
                    "+" if isinstance(ret, int) and ret > 0 else ret]
        if op == "wait_readable":
            return [op, [sorted(tok(f) for f in a[0])],
                    sorted(tok(f) for f in ret) if isinstance(
                        ret, (list, tuple)) else ret]
        if op == "poll":
            cargs = [sorted([tok(f), int(e)] for f, e in a[0]), int(a[1])]
            cret = sorted([tok(f), int(e)] for f, e in ret) \
                if isinstance(ret, (list, tuple)) else ret
            return [op, cargs, cret]
        if op == "select":
            cargs = [sorted(tok(f) for f in a[0]),
                     sorted(tok(f) for f in a[1]), int(a[2])]
            if isinstance(ret, tuple) and len(ret) == 2:
                cret = [sorted(tok(f) for f in ret[0]),
                        sorted(tok(f) for f in ret[1])]
            else:
                cret = _jsonable(ret)
            return [op, cargs, cret]
        if op == "epoll_create":
            return [op, [], tok(ret)]
        if op == "epoll_ctl":
            return [op, [tok(a[0]), int(a[1]), tok(a[2]), int(a[3])],
                    ret]
        if op == "epoll_wait":
            cret = sorted([tok(f), int(e)] for f, e in ret) \
                if isinstance(ret, (list, tuple)) else ret
            return [op, [tok(a[0])], cret]
        if op == "fopen":
            return [op, [a[0], a[1]], tok(ret)]
        if op in ("fseek", "fstat_size"):
            return [op, [tok(a[0])] + [int(x) for x in a[1:]], ret]
        if op == "getrandom":
            return [op, [int(a[0])],
                    _digest(ret) if isinstance(ret, (bytes, bytearray))
                    else ret]
        if op == "write":
            # fds 1/2 are stdio ONLY when no live socket token claims
            # them — the socket fd space starts at 0 and overlaps (a
            # `write` never targets a socket, so a tokenized 1/2 here
            # means slot numbering, not stdio)
            if a[0] in (1, 2) and a[0] not in self._tok:
                t = "stdout" if a[0] == 1 else "stderr"
            else:
                t = tok(a[0])
            if len(a) > 1:
                return [op, [t, _digest(a[1])], ret]
            return [op, [t], ret]
        if op == "read":
            return [op, [tok(a[0])],
                    _digest(ret) if isinstance(ret, (bytes, bytearray))
                    else ret]
        if op == "sigaction":
            return [op, [int(a[0]), "handler"], ret]
        if op == "thread_create":
            return [op, ["fn"], ret]
        if op in ("pipe", "socketpair"):
            cret = [tok(ret[0]), tok(ret[1])] \
                if isinstance(ret, tuple) else ret
            return [op, [], cret]
        if op == "_exit":
            return [op, [], _jsonable(ret)]
        # default: mutex/cond/thread_join/kill/raise_sig/funlink/
        # c_rand/getpid/gethostname/fork/exec/system/errno — args are
        # already stable ints/strings across backends
        return [op, [_jsonable(x) for x in a], _jsonable(ret)]
