"""Dual-mode harness: run one reference workload (apps/reftests.py)
under BOTH backends — the TPU-oriented simulation (ProcessRuntime)
and the real host kernel (HostKernelExecutor) — with a trace recorder
attached to each, and diff the normalized traces.

The workload registry below is the single catalog of which reference
syscall tests run dual-mode: the program source is SHARED (the point
of the subsystem — apps/ is untouched), only the spawn placement,
arguments, and sim horizon are per-workload. `host_ok=False` marks
the documented sim-only cases (docs/7-conformance.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from shadow_tpu.core import simtime
from shadow_tpu.net.build import HostSpec, build
from shadow_tpu.net.state import NetConfig

from .diff import DiffResult, diff_traces
from .executor import HostKernelExecutor
from .kernel import PortAllocator, PortMap
from .trace import TraceRecorder

# the same tiny 2-host topology tests/test_vproc.py exercises — both
# backends build it, so env['resolve'] hands programs identical IPs
GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="lat" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="up" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="dn" />
  <key attr.name="type" attr.type="string" for="node" id="ty" />
  <graph edgedefault="undirected">
    <node id="a"><data key="up">10240</data><data key="dn">10240</data>
      <data key="ty">client</data></node>
    <node id="b"><data key="up">10240</data><data key="dn">10240</data>
      <data key="ty">server</data></node>
    <edge source="a" target="a"><data key="lat">5.0</data></edge>
    <edge source="a" target="b"><data key="lat">25.0</data></edge>
    <edge source="b" target="b"><data key="lat">5.0</data></edge>
  </graph>
</graphml>"""

HOST_NAMES = ("client", "server")


@dataclass(frozen=True)
class Workload:
    """One reference test in the dual-mode catalog. `procs` places
    generator instances: (host_index, argv, start_seconds)."""

    name: str
    fn_name: str                      # attribute in apps.reftests
    seconds: int = 10
    procs: tuple = ((0, (), 0),)
    host_ok: bool = True
    slow: bool = False
    note: str = ""


WORKLOADS = {
    w.name: w for w in (
        Workload("bind", "bind_main"),
        Workload("epoll", "epoll_main"),
        Workload("poll", "poll_main"),
        Workload("sockbuf", "sockbuf_main",
                 note="getsockopt returns the user-set value on both "
                      "backends (Linux doubling masked)"),
        Workload("timerfd", "timerfd_main", seconds=15),
        Workload("sleep", "sleep_main", host_ok=False,
                 note="asserts an EXACT 1 s virtual delta; a real "
                      "clock cannot satisfy it"),
        Workload("shutdown", "shutdown_main", seconds=20, slow=True),
        Workload("epoll_writeable", "epoll_writeable_main", seconds=40,
                 procs=((1, ("server",), 0), (0, ("client", "server"), 1)),
                 slow=True),
        Workload("file", "file_main"),
        Workload("random", "random_main"),
        Workload("signal", "signal_main"),
        Workload("pthreads", "pthreads_main"),
        Workload("unistd", "unistd_main"),
        # the open-system traffic model (apps/tgen.py): the SAME
        # phase walk that compiles <traffic> injection traces drives
        # real sendto calls here, so the workload's wire behavior is
        # conformance-gated before the injection path replays it
        Workload("tgen", "tgen_main", seconds=10,
                 procs=((1, ("server",), 0),
                        (0, ("client", "server"), 1))),
    )
}

#: catalog slices tests and tools iterate over
DUAL_WORKLOADS = tuple(w.name for w in WORKLOADS.values() if w.host_ok)
FAST_DUAL_WORKLOADS = tuple(w.name for w in WORKLOADS.values()
                            if w.host_ok and not w.slow)
SIM_ONLY_WORKLOADS = tuple(w.name for w in WORKLOADS.values()
                           if not w.host_ok)


def _bundle(seconds: int, seed: int = 1):
    cfg = NetConfig(num_hosts=2, end_time=seconds * simtime.ONE_SECOND,
                    seed=seed)
    hosts = [HostSpec(name=HOST_NAMES[0], type="client"),
             HostSpec(name=HOST_NAMES[1], type="server")]
    return build(cfg, GRAPH, hosts)


def _env(bundle, hi: int, args) -> dict:
    # the loader's plugin env contract (config/loader.py:_vproc_entry)
    return {
        "host": bundle.host_names[hi],
        "host_index": hi,
        "args": list(args),
        "resolve": bundle.ip_of,
        "hosts": bundle.host_names,
        "cfg": bundle.cfg,
    }


def _resolve_fn(w: Workload):
    from shadow_tpu.apps import reftests

    return getattr(reftests, w.fn_name)


def _spawn_all(w: Workload, bundle, target):
    fn = _resolve_fn(w)
    for hi, args, start_s in w.procs:
        env = _env(bundle, hi, args)
        target.spawn(hi, (lambda _h, m=fn, e=env: m(e)),
                     start_time=start_s * simtime.ONE_SECOND)


def _recorder(bundle) -> TraceRecorder:
    return TraceRecorder(ip_names={int(bundle.ip_of(n)): n
                                   for n in bundle.host_names})


def run_sim(name: str, seed: int = 1):
    """Run one cataloged workload under the simulation; returns the
    attached TraceRecorder."""
    from shadow_tpu.process.vproc import ProcessRuntime

    w = WORKLOADS[name]
    bundle = _bundle(w.seconds, seed)
    rt = ProcessRuntime(bundle)
    rec = _recorder(bundle)
    rt.trace = rec
    _spawn_all(w, bundle, rt)
    rt.run()
    return rec


def run_host(name: str, seed: int = 1, time_scale: float = 0.05):
    """Run one cataloged workload on the real host kernel; returns
    the attached TraceRecorder. Raises PortsUnavailable in sandboxes
    with no bindable loopback ports (callers skip, not flake), and
    ValueError for sim-only workloads."""
    w = WORKLOADS[name]
    if not w.host_ok:
        raise ValueError(
            f"workload {name!r} is sim-only: {w.note or 'see catalog'}")
    PortAllocator.preflight()
    bundle = _bundle(w.seconds, seed)
    rec = _recorder(bundle)
    ex = HostKernelExecutor(
        bundle, time_scale=time_scale, trace=rec,
        portmap=PortMap(PortAllocator(seed=seed)))
    _spawn_all(w, bundle, ex)
    ex.run()
    return rec


@dataclass
class DualResult:
    name: str
    diff: DiffResult
    sim: dict = field(default_factory=dict)
    host: dict = field(default_factory=dict)


def run_dual(name: str, seed: int = 1,
             time_scale: float = 0.05) -> DualResult:
    """Run one workload both ways and diff the normalized traces."""
    sim_rec = run_sim(name, seed)
    host_rec = run_host(name, seed, time_scale)
    sim_n = sim_rec.normalized()
    host_n = host_rec.normalized()
    return DualResult(name=name, diff=diff_traces(sim_n, host_n),
                      sim=sim_n, host=host_n)


def conformance_block(names, seed: int = 1, time_scale: float = 0.05,
                      results: dict | None = None) -> dict:
    """Run `names` dual-mode and produce the run-manifest conformance
    block: per-workload verdicts plus agree/diverge counts. Workload
    errors are verdicts too ("error: ..."), not raised — a manifest
    should record a bad run, not abort on it. Pass `results` to
    collect each DualResult for reporting."""
    verdicts = {}
    agree = diverge = 0
    for name in names:
        try:
            res = run_dual(name, seed=seed, time_scale=time_scale)
        except Exception as e:      # noqa: BLE001 — verdict, not crash
            verdicts[name] = f"error: {type(e).__name__}: {e}"
            diverge += 1
            continue
        if results is not None:
            results[name] = res
        if res.diff.agree:
            verdicts[name] = "agree"
            agree += 1
        else:
            verdicts[name] = "diverge"
            diverge += 1
    return {"workloads": verdicts, "agree": agree, "diverge": diverge,
            "total": len(verdicts)}
