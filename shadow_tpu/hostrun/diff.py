"""Differential checker: compare normalized traces from the two
backends (hostrun/trace.py) and summarize agreement.

Comparison is exact on the canonical form — the tolerance for
legitimate timing divergence (ready-set ordering, partial-transfer
chunking, clock values, ephemeral ports, expiration counts) lives in
the normalizer, not here, so every rule is written down in one place
(docs/7-conformance.md) and the checker itself stays a strict
sequence equality with readable reporting.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class DiffResult:
    agree: bool
    divergences: list = field(default_factory=list)
    stats: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"agree": self.agree, "divergences": self.divergences,
                "stats": self.stats}


def _observables(procs: dict) -> dict:
    """Roll-up per side: bytes moved, -1 returns, accepts, exits."""
    sent = received = accepts = errnos = 0
    exits = {}
    for proc, recs in procs.items():
        for rec in recs:
            op, _args, ret = rec
            if op in ("send", "send_data") and isinstance(ret, int) \
                    and ret > 0:
                sent += ret
            elif op == "recv" and isinstance(ret, int) and ret > 0:
                received += ret
            elif op in ("recv_data", "read") and isinstance(ret, list) \
                    and len(ret) == 2 and isinstance(ret[0], int):
                received += ret[0]
            elif op == "accept" and ret != -1:
                accepts += 1
            elif op == "_exit":
                exits[proc] = ret
            if ret == -1:
                errnos += 1
    return {"bytes_sent": sent, "bytes_received": received,
            "accepts": accepts, "error_returns": errnos, "exits": exits}


def diff_traces(sim_procs: dict, host_procs: dict) -> DiffResult:
    """Compare two normalized {proc: [records]} maps. Divergences
    carry enough context to localize the first disagreement per
    process; stats carry both sides' observables regardless."""
    div = []
    for proc in sorted(set(sim_procs) | set(host_procs)):
        a = sim_procs.get(proc)
        b = host_procs.get(proc)
        if a is None or b is None:
            div.append({"proc": proc, "index": None,
                        "kind": "missing-process",
                        "sim": None if a is None else len(a),
                        "host": None if b is None else len(b)})
            continue
        for i, (ra, rb) in enumerate(zip(a, b)):
            if ra != rb:
                div.append({"proc": proc, "index": i,
                            "kind": "record-mismatch",
                            "sim": ra, "host": rb})
                break               # first mismatch per proc: the
                # rest of the sequence diverges by construction
        else:
            if len(a) != len(b):
                div.append({"proc": proc, "index": min(len(a), len(b)),
                            "kind": "length-mismatch",
                            "sim": len(a), "host": len(b)})
    obs_sim = _observables(sim_procs)
    obs_host = _observables(host_procs)
    if not div and obs_sim != obs_host:
        div.append({"proc": "*", "index": None,
                    "kind": "observables-mismatch",
                    "sim": obs_sim, "host": obs_host})
    return DiffResult(
        agree=not div, divergences=div,
        stats={"procs": len(set(sim_procs) | set(host_procs)),
               "records_sim": sum(map(len, sim_procs.values())),
               "records_host": sum(map(len, host_procs.values())),
               "sim": obs_sim, "host": obs_host})


def render(res: DiffResult, label_a: str = "sim",
           label_b: str = "host") -> str:
    """Human-readable divergence report (tools/dualmode_diff.py)."""
    lines = []
    s = res.stats
    lines.append(
        f"{'AGREE' if res.agree else 'DIVERGE'}: "
        f"{s.get('procs', 0)} proc(s), "
        f"{s.get('records_sim', 0)} {label_a} / "
        f"{s.get('records_host', 0)} {label_b} records")
    for side, label in ((s.get("sim"), label_a), (s.get("host"), label_b)):
        if side:
            lines.append(
                f"  {label}: sent={side['bytes_sent']} "
                f"recv={side['bytes_received']} "
                f"accepts={side['accepts']} "
                f"errs={side['error_returns']}")
    for d in res.divergences:
        if d["kind"] == "missing-process":
            lines.append(f"  !! {d['proc']}: present only in "
                         f"{label_a if d['host'] is None else label_b}")
        elif d["kind"] == "length-mismatch":
            lines.append(
                f"  !! {d['proc']}: record counts differ after index "
                f"{d['index']} ({label_a}={d['sim']}, "
                f"{label_b}={d['host']})")
        elif d["kind"] == "observables-mismatch":
            lines.append(f"  !! observables differ: {label_a}={d['sim']} "
                         f"{label_b}={d['host']}")
        else:
            lines.append(f"  !! {d['proc']}[{d['index']}]:")
            lines.append(f"       {label_a}:  {d['sim']}")
            lines.append(f"       {label_b}: {d['host']}")
    return "\n".join(lines)
