// Host-side payload pool — the native counterpart of the reference's
// refcounted shared Payload (ref: payload.c:17-30: a mutex-guarded
// refcounted byte buffer so packet copies share one payload across
// threads). Device packets carry only a payloadRef int32 (SURVEY.md
// §7.2); the bytes live here. ref() on send, unref() on final
// delivery/drop; slots are recycled through a free list so the id
// space stays dense (int32-addressable from device words).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Slot {
  std::vector<uint8_t> data;
  int32_t refs = 0;
};

struct Pool {
  std::mutex mu;
  std::vector<Slot> slots;
  std::vector<int32_t> free_list;
  int64_t live_bytes = 0;
  int64_t total_allocs = 0;
};

}  // namespace

extern "C" {

void* payload_pool_new() { return new Pool(); }

void payload_pool_free(void* p) { delete static_cast<Pool*>(p); }

// store bytes, returns payload ref (>= 0) with refcount 1
int32_t payload_pool_put(void* p, const uint8_t* data, int64_t len) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  int32_t id;
  if (!pool->free_list.empty()) {
    id = pool->free_list.back();
    pool->free_list.pop_back();
  } else {
    id = static_cast<int32_t>(pool->slots.size());
    pool->slots.emplace_back();
  }
  Slot& s = pool->slots[id];
  s.data.assign(data, data + len);
  s.refs = 1;
  pool->live_bytes += len;
  pool->total_allocs++;
  return id;
}

int32_t payload_pool_ref(void* p, int32_t id) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  if (id < 0 || id >= (int32_t)pool->slots.size()) return -1;
  return ++pool->slots[id].refs;
}

int32_t payload_pool_unref(void* p, int32_t id) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  if (id < 0 || id >= (int32_t)pool->slots.size()) return -1;
  Slot& s = pool->slots[id];
  if (s.refs <= 0) return -1;
  if (--s.refs == 0) {
    pool->live_bytes -= static_cast<int64_t>(s.data.size());
    s.data.clear();
    s.data.shrink_to_fit();
    pool->free_list.push_back(id);
  }
  return s.refs;
}

int64_t payload_pool_len(void* p, int32_t id) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  if (id < 0 || id >= (int32_t)pool->slots.size()) return -1;
  return static_cast<int64_t>(pool->slots[id].data.size());
}

// copy out up to cap bytes; returns copied count
int64_t payload_pool_get(void* p, int32_t id, uint8_t* out, int64_t cap) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  if (id < 0 || id >= (int32_t)pool->slots.size()) return -1;
  const Slot& s = pool->slots[id];
  int64_t n = std::min<int64_t>(cap, s.data.size());
  std::memcpy(out, s.data.data(), n);
  return n;
}

int64_t payload_pool_live_bytes(void* p) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  return pool->live_bytes;
}

int64_t payload_pool_total_allocs(void* p) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  return pool->total_allocs;
}

int64_t payload_pool_live_count(void* p) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  int64_t n = 0;
  for (const Slot& s : pool->slots) n += (s.refs > 0);
  return n;
}

int64_t payload_pool_live_ids(void* p, int32_t* out, int64_t cap) {
  Pool* pool = static_cast<Pool*>(p);
  std::lock_guard<std::mutex> lock(pool->mu);
  int64_t n = 0;
  for (size_t i = 0; i < pool->slots.size() && n < cap; ++i) {
    if (pool->slots[i].refs > 0) out[n++] = static_cast<int32_t>(i);
  }
  return n;
}

}  // extern "C"
