// Log-record time sorter — the native counterpart of the reference's
// logger helper thread (ref: logger_helper.c:50-66: merge/sort
// buffered LogRecords by sim time before writing). The Python
// SimLogger falls back to list.sort(); at heavy log volume this
// stable (time, seq) argsort over parallel arrays is the hot path.

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <vector>

extern "C" {

// stable argsort of (times[i], seqs[i]); writes permutation into out
void logsort_argsort(const int64_t* times, const int64_t* seqs, int64_t n,
                     int64_t* out) {
  std::vector<int64_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  std::stable_sort(idx.begin(), idx.end(),
                   [&](int64_t a, int64_t b) {
                     if (times[a] != times[b]) return times[a] < times[b];
                     return seqs[a] < seqs[b];
                   });
  std::copy(idx.begin(), idx.end(), out);
}

}  // extern "C"
