// Interval-set retransmission tally — the native counterpart of the
// reference's only core C++ component (ref:
// src/main/host/descriptor/tcp_retransmit_tally.{cc,h}): tracks
// sacked / retransmitted / marked-lost sequence ranges as sorted,
// coalesced [begin, end) interval vectors and computes the lost
// ranges below the recovery point (RACK-style: lost = in
// [snd_una, recovery_point), not sacked, given >= 3 duplicate acks —
// ref: tcp_retransmit_tally.h:52-76 kDuplAckLostThresh).
//
// Exposed through a C ABI (ref: the retransmit_tally_* wrappers,
// tcp_retransmit_tally.h:29-50) and consumed from Python via ctypes
// (shadow_tpu/native/tally.py). The device TCP engine keeps a reduced
// 3-range advertised-list scoreboard on-chip (net/tcp.py sack_l/r +
// sack_clip_len); this native tally is its full-fidelity
// differential-validation ORACLE: tests/test_tally_oracle.py drives
// both with the same heavy-loss packet streams and asserts the
// device's retransmit decisions match the interval-set computation.

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

using Range = std::pair<int64_t, int64_t>;  // [begin, end)
using Ranges = std::vector<Range>;

constexpr int kDuplAckLostThresh = 3;  // ref: tcp_retransmit_tally.h

// insert [b, e) keeping the vector sorted and coalesced
void insert_range(Ranges* rs, int64_t b, int64_t e) {
  if (b >= e) return;
  Ranges out;
  out.reserve(rs->size() + 1);
  bool placed = false;
  for (const Range& r : *rs) {
    if (r.second < b) {
      out.push_back(r);
    } else if (e < r.first) {
      if (!placed) {
        out.emplace_back(b, e);
        placed = true;
      }
      out.push_back(r);
    } else {  // overlap/adjacent: merge into the pending range
      b = std::min(b, r.first);
      e = std::max(e, r.second);
    }
  }
  if (!placed) out.emplace_back(b, e);
  std::sort(out.begin(), out.end());
  *rs = std::move(out);
}

// remove everything below `seq` (cumulative ACK advance)
void trim_below(Ranges* rs, int64_t seq) {
  Ranges out;
  for (const Range& r : *rs) {
    if (r.second <= seq) continue;
    out.emplace_back(std::max(r.first, seq), r.second);
  }
  *rs = std::move(out);
}

bool contains(const Ranges& rs, int64_t b, int64_t e) {
  for (const Range& r : rs)
    if (r.first <= b && e <= r.second) return true;
  return false;
}

struct Tally {
  int64_t snd_una = 0;
  int64_t recovery_point = -1;
  int num_dupl_acks = 0;
  Ranges sacked;
  Ranges retransmitted;
  Ranges marked_lost;  // explicit (timeout) loss marks
};

// lost = [snd_una, recovery_point) minus sacked, when the dup-ack
// threshold has been reached or loss was marked explicitly
// (ref: tcp_retransmit_tally.cc compute_lost)
void compute_lost(const Tally& t, Ranges* lost) {
  lost->clear();
  for (const Range& r : t.marked_lost)
    insert_range(lost, r.first, r.second);
  if (t.recovery_point >= 0 && t.num_dupl_acks >= kDuplAckLostThresh) {
    int64_t cur = t.snd_una;
    int64_t end = t.recovery_point;
    for (const Range& s : t.sacked) {
      if (s.second <= cur) continue;
      if (s.first >= end) break;
      if (s.first > cur) insert_range(lost, cur, std::min(s.first, end));
      cur = std::max(cur, s.second);
      if (cur >= end) break;
    }
    if (cur < end) insert_range(lost, cur, end);
  }
  // sacked bytes are never lost (explicit timeout marks can cover
  // them: ref compute_lost subtracts sacked_ from marked_lost_), and
  // never report retransmitted-and-not-again-lost ranges
  auto subtract = [lost](const Ranges& minus) {
    for (const Range& r : minus) {
      Ranges out;
      for (const Range& l : *lost) {
        if (l.second <= r.first || r.second <= l.first) {
          out.push_back(l);
          continue;
        }
        if (l.first < r.first) out.emplace_back(l.first, r.first);
        if (r.second < l.second) out.emplace_back(r.second, l.second);
      }
      *lost = std::move(out);
    }
  };
  subtract(t.sacked);
  subtract(t.retransmitted);
}

}  // namespace

extern "C" {

void* retransmit_tally_new(int64_t snd_una) {
  Tally* t = new Tally();
  t->snd_una = snd_una;
  return t;
}

void retransmit_tally_free(void* p) { delete static_cast<Tally*>(p); }

void retransmit_tally_sacked(void* p, int64_t begin, int64_t end) {
  insert_range(&static_cast<Tally*>(p)->sacked, begin, end);
}

void retransmit_tally_retransmitted(void* p, int64_t begin, int64_t end) {
  insert_range(&static_cast<Tally*>(p)->retransmitted, begin, end);
}

void retransmit_tally_mark_lost(void* p, int64_t begin, int64_t end) {
  insert_range(&static_cast<Tally*>(p)->marked_lost, begin, end);
}

void retransmit_tally_dupl_ack(void* p) {
  static_cast<Tally*>(p)->num_dupl_acks++;
}

void retransmit_tally_set_recovery_point(void* p, int64_t seq) {
  static_cast<Tally*>(p)->recovery_point = seq;
}

// cumulative ACK advance: drop state below snd_una, reset dup-acks
void retransmit_tally_advance(void* p, int64_t snd_una) {
  Tally* t = static_cast<Tally*>(p);
  if (snd_una <= t->snd_una) {
    t->num_dupl_acks++;
    return;
  }
  t->snd_una = snd_una;
  t->num_dupl_acks = 0;
  trim_below(&t->sacked, snd_una);
  trim_below(&t->retransmitted, snd_una);
  trim_below(&t->marked_lost, snd_una);
  if (t->recovery_point >= 0 && snd_una >= t->recovery_point)
    t->recovery_point = -1;
}

int retransmit_tally_is_sacked(void* p, int64_t begin, int64_t end) {
  return contains(static_cast<Tally*>(p)->sacked, begin, end) ? 1 : 0;
}

// fills out_begins/out_ends (capacity `cap`), returns count
// (ref: retransmit_tally_populate_lost_ranges)
int retransmit_tally_lost_ranges(void* p, int64_t* out_begins,
                                 int64_t* out_ends, int cap) {
  Ranges lost;
  compute_lost(*static_cast<Tally*>(p), &lost);
  int n = 0;
  for (const Range& r : lost) {
    if (n >= cap) break;
    out_begins[n] = r.first;
    out_ends[n] = r.second;
    n++;
  }
  return n;
}

int64_t retransmit_tally_sacked_bytes(void* p) {
  int64_t total = 0;
  for (const Range& r : static_cast<Tally*>(p)->sacked)
    total += r.second - r.first;
  return total;
}

}  // extern "C"
