"""RetransmitTally — Python face of the native interval-set
scoreboard (ref: tcp_retransmit_tally.h:29-50 C ABI), with a pure
Python fallback implementing identical semantics."""

from __future__ import annotations

import ctypes

from shadow_tpu.native import load

DUPL_ACK_LOST_THRESH = 3  # ref: tcp_retransmit_tally.h kDuplAckLostThresh


class _PyTally:
    """Fallback with the same behavior as retransmit_tally.cc."""

    def __init__(self, snd_una: int):
        self.snd_una = snd_una
        self.recovery_point = -1
        self.dupl_acks = 0
        self.sacked: list[tuple[int, int]] = []
        self.retransmitted: list[tuple[int, int]] = []
        self.marked: list[tuple[int, int]] = []

    @staticmethod
    def _insert(rs, b, e):
        if b >= e:
            return
        out = []
        for rb, re in rs:
            if re < b or e < rb:
                out.append((rb, re))
            else:
                b, e = min(b, rb), max(e, re)
        out.append((b, e))
        out.sort()
        rs[:] = out

    @staticmethod
    def _trim(rs, seq):
        rs[:] = [(max(b, seq), e) for b, e in rs if e > seq]

    def mark_sacked(self, b, e):
        self._insert(self.sacked, b, e)

    def mark_retransmitted(self, b, e):
        self._insert(self.retransmitted, b, e)

    def mark_lost(self, b, e):
        self._insert(self.marked, b, e)

    def dupl_ack(self):
        self.dupl_acks += 1

    def set_recovery_point(self, seq):
        self.recovery_point = seq

    def advance(self, snd_una):
        if snd_una <= self.snd_una:
            self.dupl_acks += 1
            return
        self.snd_una = snd_una
        self.dupl_acks = 0
        for rs in (self.sacked, self.retransmitted, self.marked):
            self._trim(rs, snd_una)
        if self.recovery_point >= 0 and snd_una >= self.recovery_point:
            self.recovery_point = -1

    def is_sacked(self, b, e):
        return any(rb <= b and e <= re for rb, re in self.sacked)

    def sacked_bytes(self):
        return sum(e - b for b, e in self.sacked)

    def lost_ranges(self):
        lost: list[tuple[int, int]] = []
        for r in self.marked:
            self._insert(lost, *r)
        if (self.recovery_point >= 0
                and self.dupl_acks >= DUPL_ACK_LOST_THRESH):
            cur, end = self.snd_una, self.recovery_point
            for sb, se in self.sacked:
                if se <= cur:
                    continue
                if sb >= end:
                    break
                if sb > cur:
                    self._insert(lost, cur, min(sb, end))
                cur = max(cur, se)
                if cur >= end:
                    break
            if cur < end:
                self._insert(lost, cur, end)
        # sacked bytes are never lost (explicit marks can cover them:
        # ref compute_lost subtracts sacked_ from marked_lost_), nor
        # are retransmitted-and-not-again-lost ranges
        for rb, re in list(self.sacked) + list(self.retransmitted):
            out = []
            for lb, le in lost:
                if le <= rb or re <= lb:
                    out.append((lb, le))
                    continue
                if lb < rb:
                    out.append((lb, rb))
                if re < le:
                    out.append((re, le))
            lost = out
        return lost


class RetransmitTally:
    """Uses the native library when available, _PyTally otherwise."""

    MAX_RANGES = 64

    def __init__(self, snd_una: int = 0):
        self._lib = load()
        if self._lib is not None:
            self._h = self._lib.retransmit_tally_new(snd_una)
            self._py = None
        else:
            self._h = None
            self._py = _PyTally(snd_una)

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._h:
            self._lib.retransmit_tally_free(self._h)
            self._h = None

    @property
    def native(self) -> bool:
        return self._py is None

    def mark_sacked(self, b, e):
        if self._py:
            return self._py.mark_sacked(b, e)
        self._lib.retransmit_tally_sacked(self._h, b, e)

    def mark_retransmitted(self, b, e):
        if self._py:
            return self._py.mark_retransmitted(b, e)
        self._lib.retransmit_tally_retransmitted(self._h, b, e)

    def mark_lost(self, b, e):
        if self._py:
            return self._py.mark_lost(b, e)
        self._lib.retransmit_tally_mark_lost(self._h, b, e)

    def dupl_ack(self):
        if self._py:
            return self._py.dupl_ack()
        self._lib.retransmit_tally_dupl_ack(self._h)

    def set_recovery_point(self, seq):
        if self._py:
            return self._py.set_recovery_point(seq)
        self._lib.retransmit_tally_set_recovery_point(self._h, seq)

    def advance(self, snd_una):
        if self._py:
            return self._py.advance(snd_una)
        self._lib.retransmit_tally_advance(self._h, snd_una)

    def is_sacked(self, b, e) -> bool:
        if self._py:
            return self._py.is_sacked(b, e)
        return bool(self._lib.retransmit_tally_is_sacked(self._h, b, e))

    def sacked_bytes(self) -> int:
        if self._py:
            return self._py.sacked_bytes()
        return int(self._lib.retransmit_tally_sacked_bytes(self._h))

    def lost_ranges(self) -> list[tuple[int, int]]:
        if self._py:
            return self._py.lost_ranges()
        n = self.MAX_RANGES
        begins = (ctypes.c_int64 * n)()
        ends = (ctypes.c_int64 * n)()
        k = self._lib.retransmit_tally_lost_ranges(self._h, begins, ends, n)
        return [(int(begins[i]), int(ends[i])) for i in range(k)]
