"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its hot irregular bookkeeping native (the C++
retransmit tally, tcp_retransmit_tally.cc; glib C for everything
else). This package mirrors that split: JAX/XLA owns the device
compute path, and host-side runtime pieces with irregular data
structures live in libshadow_native.so:

- retransmit tally: interval-set SACK/loss scoreboard (tally.py)
- payload pool: refcounted byte store behind device payloadRef ids
  (pool.py)
- logsort: stable (time, seq) argsort for the log writer

The library builds on demand with `make` (g++ is part of the
toolchain); everything has a pure-Python fallback so the package
works where a compiler is unavailable.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

_DIR = pathlib.Path(__file__).resolve().parent
_LIB_PATH = _DIR / "libshadow_native.so"

_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(["make", "-s", "-C", str(_DIR)], check=True,
                       capture_output=True, timeout=120)
        return _LIB_PATH.exists()
    except Exception:
        return False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if
    unavailable — callers fall back to Python implementations."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _LIB_PATH.exists() and not _build():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
    except OSError:
        return None
    # signatures
    i64, i32, vp = ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    lib.retransmit_tally_new.restype = vp
    lib.retransmit_tally_new.argtypes = [i64]
    lib.retransmit_tally_free.argtypes = [vp]
    for f in ("sacked", "retransmitted", "mark_lost"):
        fn = getattr(lib, f"retransmit_tally_{f}")
        fn.argtypes = [vp, i64, i64]
    lib.retransmit_tally_dupl_ack.argtypes = [vp]
    lib.retransmit_tally_set_recovery_point.argtypes = [vp, i64]
    lib.retransmit_tally_advance.argtypes = [vp, i64]
    lib.retransmit_tally_is_sacked.restype = i32
    lib.retransmit_tally_is_sacked.argtypes = [vp, i64, i64]
    lib.retransmit_tally_lost_ranges.restype = i32
    lib.retransmit_tally_lost_ranges.argtypes = [vp, p_i64, p_i64, i32]
    lib.retransmit_tally_sacked_bytes.restype = i64
    lib.retransmit_tally_sacked_bytes.argtypes = [vp]

    lib.payload_pool_new.restype = vp
    lib.payload_pool_free.argtypes = [vp]
    lib.payload_pool_put.restype = i32
    lib.payload_pool_put.argtypes = [vp, p_u8, i64]
    lib.payload_pool_ref.restype = i32
    lib.payload_pool_ref.argtypes = [vp, i32]
    lib.payload_pool_unref.restype = i32
    lib.payload_pool_unref.argtypes = [vp, i32]
    lib.payload_pool_len.restype = i64
    lib.payload_pool_len.argtypes = [vp, i32]
    lib.payload_pool_get.restype = i64
    lib.payload_pool_get.argtypes = [vp, i32, p_u8, i64]
    lib.payload_pool_live_bytes.restype = i64
    lib.payload_pool_live_bytes.argtypes = [vp]
    lib.payload_pool_total_allocs.restype = i64
    lib.payload_pool_total_allocs.argtypes = [vp]
    lib.payload_pool_live_count.restype = i64
    lib.payload_pool_live_count.argtypes = [vp]
    lib.payload_pool_live_ids.restype = i64
    lib.payload_pool_live_ids.argtypes = [vp, ctypes.POINTER(i32), i64]

    lib.logsort_argsort.argtypes = [p_i64, p_i64, i64, p_i64]
    _lib = lib
    return _lib
