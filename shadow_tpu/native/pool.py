"""PayloadPool — Python face of the native refcounted byte store
(ref: payload.c semantics; device packets carry int32 payload refs,
SURVEY.md §7.2), with a dict-based fallback."""

from __future__ import annotations

import ctypes

from shadow_tpu.native import load


class PayloadPool:
    def __init__(self):
        self._lib = load()
        if self._lib is not None:
            self._h = self._lib.payload_pool_new()
            self._py = None
        else:
            self._h = None
            self._py = {}
            self._refs = {}
            self._next = 0
            self._free: list[int] = []
            self._live = 0
            self._allocs = 0

    def __del__(self):
        if getattr(self, "_lib", None) is not None and self._h:
            self._lib.payload_pool_free(self._h)
            self._h = None

    @property
    def native(self) -> bool:
        return self._py is None

    def put(self, data: bytes) -> int:
        if self._py is not None:
            pid = self._free.pop() if self._free else self._next
            if pid == self._next:
                self._next += 1
            self._py[pid] = data
            self._refs[pid] = 1
            self._live += len(data)
            self._allocs += 1
            return pid
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return int(self._lib.payload_pool_put(self._h, buf, len(data)))

    def ref(self, pid: int) -> int:
        if self._py is not None:
            self._refs[pid] += 1
            return self._refs[pid]
        return int(self._lib.payload_pool_ref(self._h, pid))

    def unref(self, pid: int) -> int:
        if self._py is not None:
            self._refs[pid] -= 1
            if self._refs[pid] == 0:
                self._live -= len(self._py.pop(pid))
                self._free.append(pid)
            return self._refs.get(pid, 0)
        return int(self._lib.payload_pool_unref(self._h, pid))

    def get(self, pid: int) -> bytes:
        if self._py is not None:
            return self._py[pid]
        n = int(self._lib.payload_pool_len(self._h, pid))
        if n < 0:
            raise KeyError(pid)
        buf = (ctypes.c_uint8 * n)()
        got = int(self._lib.payload_pool_get(self._h, pid, buf, n))
        return bytes(buf[:got])

    def live_bytes(self) -> int:
        if self._py is not None:
            return self._live
        return int(self._lib.payload_pool_live_bytes(self._h))

    def live_refs(self) -> int:
        """Entries still held (object-counter leak accounting)."""
        if self._py is not None:
            return len(self._py)
        return int(self._lib.payload_pool_live_count(self._h))

    def live_ids(self) -> list:
        """Ids of entries still held (mark-sweep GC support)."""
        if self._py is not None:
            return sorted(self._py)
        n = self.live_refs()
        if n == 0:
            return []
        buf = (ctypes.c_int32 * n)()
        got = int(self._lib.payload_pool_live_ids(self._h, buf, n))
        return sorted(buf[i] for i in range(got))

    def total_allocs(self) -> int:
        if self._py is not None:
            return self._allocs
        return int(self._lib.payload_pool_total_allocs(self._h))
