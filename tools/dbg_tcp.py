import warnings
warnings.simplefilter("error", FutureWarning)
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from shadow_tpu.apps import bulk
from shadow_tpu.core import simtime
from shadow_tpu.net import tcp
from shadow_tpu.net.build import HostSpec, build, run
from shadow_tpu.net.state import NetConfig

GRAPH = open("tests/test_tcp.py").read().split('GRAPH = """')[1].split('"""')[0]
GRAPH = GRAPH.replace("{LOSS}", "0.0")

cfg = NetConfig(num_hosts=2, end_time=3 * simtime.ONE_SECOND, seed=1)
hosts = [
    HostSpec(name="client", type="client", proc_start_time=simtime.ONE_SECOND),
    HostSpec(name="server", type="server"),
]
b = build(cfg, GRAPH, hosts)
client = jnp.asarray(np.arange(2) == b.host_of("client"))
server = jnp.asarray(np.arange(2) == b.host_of("server"))
b.sim = bulk.setup(b.sim, client_mask=client, server_mask=server,
                   server_ip=b.ip_of("server"), server_port=8080,
                   total_bytes=5000)

with jax.disable_jit():
    sim, stats = run(b, app_handlers=(bulk.handler,))

print("events:", int(stats.events_processed), "windows:", int(stats.windows))
print("tcp st:\n", np.asarray(sim.tcp.st))
print("snd_una:", np.asarray(sim.tcp.snd_una))
print("snd_nxt:", np.asarray(sim.tcp.snd_nxt))
print("snd_end:", np.asarray(sim.tcp.snd_end))
print("rcv_nxt:", np.asarray(sim.tcp.rcv_nxt))
print("app_rbytes:", np.asarray(sim.tcp.app_rbytes))
print("rcvd:", np.asarray(sim.app.rcvd), "eof:", np.asarray(sim.app.eof))
print("to_send:", np.asarray(sim.app.to_send), "child:", np.asarray(sim.app.child))
print("tx_packets:", np.asarray(sim.net.ctr_tx_packets))
print("rx_packets:", np.asarray(sim.net.ctr_rx_packets))
print("nosock:", np.asarray(sim.net.ctr_drop_nosocket))
print("overflow ev/out:", int(sim.events.overflow), int(sim.outbox.overflow))
print("retx:", np.asarray(sim.tcp.retx_segs))
print("sk_type:\n", np.asarray(sim.net.sk_type))
print("sk_flags:\n", np.asarray(sim.net.sk_flags))
