"""Microbenchmarks of the specific ops the bulk-pass bisection
implicates: searchsorted variants, uniform_at, i64 elementwise, scans,
batched scatters."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "tpu,cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng


from tools.perfutil import timeit  # noqa: E402


def main():
    H, K, GH = 10240, 48, 10240
    print(f"backend: {jax.default_backend()}  H={H} K={K}")
    key = jax.random.PRNGKey(0)
    table = jnp.sort(jax.random.randint(key, (GH,), 0, 1 << 30,
                                        dtype=jnp.int32)).astype(jnp.int64)
    queries = jax.random.randint(key, (H, K), 0, 1 << 30,
                                 dtype=jnp.int32).astype(jnp.int64)

    for method in ["scan", "scan_unrolled", "compare_all", "sort"]:
        try:
            f = jax.jit(lambda t, q, m=method: jnp.searchsorted(t, q, method=m))
            print(f"searchsorted[{method:13s}]: {timeit(f, table, queries)*1e3:8.2f} ms")
        except Exception as e:
            print(f"searchsorted[{method}] failed: {type(e).__name__}")

    kd = jax.random.key_data(
        jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
            jax.random.key(1), jnp.arange(H, dtype=jnp.uint32)))
    ctr = jnp.broadcast_to(jnp.arange(H, dtype=jnp.uint32)[:, None], (H, K))
    print(f"uniform_at [H,K]:        {timeit(jax.jit(rng.uniform_at), kd, ctr)*1e3:8.2f} ms")

    a64 = queries
    b64 = queries * 3
    f64 = jax.jit(lambda a, b: jnp.where(a > b, a + b, a - b))
    print(f"i64 elementwise [H,K]:   {timeit(f64, a64, b64)*1e3:8.2f} ms")
    a32 = a64.astype(jnp.int32)
    b32 = b64.astype(jnp.int32)
    f32 = jax.jit(lambda a, b: jnp.where(a > b, a + b, a - b))
    print(f"i32 elementwise [H,K]:   {timeit(f32, a32, b32)*1e3:8.2f} ms")

    fc64 = jax.jit(lambda a: jnp.cumsum(a, axis=1))
    print(f"i64 cumsum [H,K]:        {timeit(fc64, a64)*1e3:8.2f} ms")
    fc32 = jax.jit(lambda a: jnp.cumsum(a, axis=1))
    print(f"i32 cumsum [H,K]:        {timeit(fc32, a32)*1e3:8.2f} ms")

    ft = jax.jit(lambda a, o: jnp.take_along_axis(a, o, axis=1))
    order = jnp.argsort(a32, axis=1)
    print(f"take_along i64 [H,K]:    {timeit(ft, a64, order)*1e3:8.2f} ms")
    print(f"take_along i32 [H,K]:    {timeit(ft, a32, order)*1e3:8.2f} ms")

    # batched 2D scatter (the place() pattern) vs flat scatter
    M = K
    lane_h = jnp.arange(H)[:, None]
    col = jnp.where(a32 % 2 == 0, order, M)
    def place(vals):
        base = jnp.full((H, M), -1, jnp.int32)
        return base.at[lane_h, col].set(vals, mode="drop")
    print(f"batched scatter [H,K]->[H,M]: {timeit(jax.jit(place), b32)*1e3:8.2f} ms")

    flat_r = jnp.repeat(jnp.arange(H), K)
    flat_c = col.reshape(-1)
    def place_flat(vals):
        base = jnp.full((H, M), -1, jnp.int32)
        return base.at[flat_r, flat_c].set(vals.reshape(-1), mode="drop")
    print(f"flat scatter [H*K]->[H,M]:    {timeit(jax.jit(place_flat), b32)*1e3:8.2f} ms")

    # gather-based alternative: invert the permutation via argsort
    def place_gather(vals):
        # out[h, m] = vals[h, k] where col[h,k] == m  (cols unique or M)
        ordc = jnp.argsort(col, axis=1)  # positions sorted by target col
        vals_s = jnp.take_along_axis(vals, ordc, axis=1)
        col_s = jnp.take_along_axis(col, ordc, axis=1)
        hit = jnp.arange(M)[None, :] == col_s[:, :M]
        return jnp.where(hit, vals_s[:, :M], -1)
    print(f"sortgather [H,K]->[H,M]:      {timeit(jax.jit(place_gather), b32)*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
