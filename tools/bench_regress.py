#!/usr/bin/env python
"""Bench regression gate: newest BENCH_r*.json vs the banked trajectory.

The driver banks one BENCH_rNN.json per round (schema: {n, cmd, rc,
tail, parsed}; `parsed` is either one bench row or a {label: row} dict
of rows, each row carrying "metric"/"value" in events/s). This tool
compares every row of the NEWEST round against the most recent prior
occurrence of the same metric — matched by (metric, backend), because
a CPU-fallback number and a TPU number under one metric name are not
comparable — and exits nonzero when any metric dropped by more than
the threshold (default 10%).

Metrics with no prior occurrence (new scenario names) pass: a gate
that fails on first appearance would punish adding coverage.

    python tools/bench_regress.py                 # repo root, 10%
    python tools/bench_regress.py --dir D --threshold 0.15
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys


def _rows(parsed) -> list:
    """Normalize a round's `parsed` field to a list of row dicts, in
    file order. Rows without a numeric value under a metric name are
    dropped (derived stats like adaptive_window_reduction bank as
    bare numbers)."""
    if isinstance(parsed, dict) and "metric" in parsed:
        cands = [parsed]
    elif isinstance(parsed, dict):
        cands = [v for v in parsed.values() if isinstance(v, dict)]
    elif isinstance(parsed, list):
        cands = [v for v in parsed if isinstance(v, dict)]
    else:
        cands = []
    out = []
    for r in cands:
        m, v = r.get("metric"), r.get("value")
        if isinstance(m, str) and isinstance(v, (int, float)):
            out.append(r)
    return out


def load_rounds(bench_dir: str) -> list:
    """[(round_n, path, [row, ...])] sorted by round number. The `n`
    field orders rounds; the filename is the fallback for hand-rolled
    files that omit it."""
    rounds = []
    for path in sorted(glob.glob(os.path.join(bench_dir,
                                              "BENCH_r*.json"))):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench_regress: skipping unreadable {path}: {e}",
                  file=sys.stderr)
            continue
        n = d.get("n")
        if not isinstance(n, int):
            stem = os.path.basename(path)
            digits = "".join(c for c in stem if c.isdigit())
            n = int(digits) if digits else 0
        rounds.append((n, path, _rows(d.get("parsed"))))
    rounds.sort(key=lambda t: (t[0], t[1]))
    return rounds


def check(rounds: list, threshold: float) -> tuple:
    """-> (regressions, comparisons). A regression is a dict naming
    the metric, both values, and both rounds. Comparison key is
    (metric, backend); the newest round's rows compare against the
    most recent PRIOR occurrence — including an earlier row of the
    same round (a fresh-then-warm pair banks twice under one name)."""
    if not rounds:
        return [], []
    *history, (new_n, new_path, new_rows) = rounds
    last_seen: dict = {}
    for n, path, rows in history:
        for r in rows:
            last_seen[(r["metric"], r.get("backend"))] = (n, r["value"])
    regressions, comparisons = [], []
    for r in new_rows:
        key = (r["metric"], r.get("backend"))
        prior = last_seen.get(key)
        if prior is not None:
            prior_n, prior_v = prior
            drop = ((prior_v - r["value"]) / prior_v if prior_v > 0
                    else 0.0)
            comparisons.append({
                "metric": r["metric"], "backend": r.get("backend"),
                "value": r["value"], "prior_value": prior_v,
                "round": new_n, "prior_round": prior_n,
                "drop_pct": round(drop * 100.0, 2),
            })
            if drop > threshold:
                regressions.append(comparisons[-1])
        # this row becomes the prior for a same-round repeat
        last_seen[key] = (new_n, r["value"])
    return regressions, comparisons


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail when the newest banked bench round regressed "
                    ">threshold vs the trajectory")
    ap.add_argument("--dir", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fractional events/s drop that fails the gate "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--require", action="append", default=[],
                    metavar="SUBSTR",
                    help="fail unless the newest round banks a metric "
                         "containing SUBSTR (repeatable). E.g. "
                         "--require _resident_ gates the resident-"
                         "program row into every round — a dropped "
                         "row would otherwise pass silently, since "
                         "absent metrics are never compared")
    args = ap.parse_args(argv)
    if not 0 < args.threshold < 1:
        print("bench_regress: --threshold must be in (0, 1)",
              file=sys.stderr)
        return 2
    rounds = load_rounds(args.dir)
    if not rounds:
        print(f"bench_regress: no BENCH_r*.json under {args.dir}; "
              f"nothing to gate")
        return 0
    regressions, comparisons = check(rounds, args.threshold)
    new_n, _, new_rows = rounds[-1]
    failed = 0
    for sub in args.require:
        if not any(sub in r["metric"] for r in new_rows):
            print(f"bench_regress: round {new_n} banks no metric "
                  f"containing {sub!r} (--require)", file=sys.stderr)
            failed = 1
    # resident-program rows (bench.py BENCH_RESIDENT) carry their
    # zero-retrace contract on the row; a broken contract fails the
    # gate even when the throughput number held up
    for r in new_rows:
        res = r.get("resident")
        if not isinstance(res, dict):
            continue
        if res.get("program_key_stable") is False:
            print(f"bench_regress: {r['metric']}: program key moved "
                  f"across an admission event (program_key_stable="
                  f"false)", file=sys.stderr)
            failed = 1
        if (res.get("retraces") or 0) > 0:
            print(f"bench_regress: {r['metric']}: resident program "
                  f"retraced {res['retraces']} time(s)",
                  file=sys.stderr)
            failed = 1
    # sweep rows (bench.py BENCH_SWEEP) carry the query-service
    # contract on the row: the scored sweep ran on a warm pool (every
    # distinct program a prewarm hit) and its lattice conserved —
    # either broken fails the gate even when points/s held up
    for r in new_rows:
        sw = r.get("sweep")
        if not isinstance(sw, dict):
            continue
        if sw.get("lattice_conserved") is False:
            print(f"bench_regress: {r['metric']}: sweep lattice not "
                  f"conserved ({sw.get('points')})", file=sys.stderr)
            failed = 1
        hr = sw.get("prewarm_hit_rate")
        if isinstance(hr, (int, float)) and not isinstance(hr, bool) \
                and hr < 1.0:
            print(f"bench_regress: {r['metric']}: scored sweep ran "
                  f"on a cold pool (prewarm_hit_rate={hr}, "
                  f"compiled={sw.get('prewarm_compiled')}) — the "
                  f"warm-up sweep must pay every compile",
                  file=sys.stderr)
            failed = 1
        for k in ("exit_warm", "exit_timed"):
            if sw.get(k) not in (0, None):
                print(f"bench_regress: {r['metric']}: {k}="
                      f"{sw.get(k)}", file=sys.stderr)
                failed = 1
    # causality-overhead rows (bench.py BENCH_CAUSALITY_OVERHEAD)
    # carry the A/B cost of the lineage recorder; tolerate absence
    # (rounds without the knob bank no such field) but gate the bound:
    # the profiler must stay under 5% of events/s at its default
    # sampling or it is not an always-on-able instrument
    for r in new_rows:
        ov = r.get("causality_overhead_pct")
        if isinstance(ov, (int, float)) and not isinstance(ov, bool) \
                and ov > 5.0:
            print(f"bench_regress: {r['metric']}: causality tracing "
                  f"costs {ov}% events/s (>5% bound)",
                  file=sys.stderr)
            failed = 1
    # sentinel-overhead rows (bench.py BENCH_SENTINEL_OVERHEAD) carry
    # the A/B cost of the cross-shard integrity screen; same rule as
    # the causality bound — an SDC screen that taxes throughput >5%
    # is not an always-on-able instrument
    for r in new_rows:
        ov = r.get("sentinel_overhead_pct")
        if isinstance(ov, (int, float)) and not isinstance(ov, bool) \
                and ov > 5.0:
            print(f"bench_regress: {r['metric']}: integrity sentinel "
                  f"costs {ov}% events/s (>5% bound)",
                  file=sys.stderr)
            failed = 1
    for c in comparisons:
        tag = "REGRESSION" if c in regressions else "ok"
        print(f"{tag}: {c['metric']} [{c['backend']}] "
              f"r{c['prior_round']:02d} {c['prior_value']} -> "
              f"r{c['round']:02d} {c['value']} "
              f"({c['drop_pct']:+.2f}% drop)")
    if not comparisons:
        print(f"bench_regress: round {new_n} has no metrics with a "
              f"banked prior; pass")
    if regressions:
        print(f"bench_regress: {len(regressions)} metric(s) regressed "
              f">{args.threshold:.0%} in round {new_n}",
              file=sys.stderr)
        return 1
    if failed:
        return 1
    print(f"bench_regress: round {new_n} within {args.threshold:.0%} "
          f"of the trajectory ({len(comparisons)} compared)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
