#!/usr/bin/env python3
"""Terminal summary of a telemetry trace / run manifest — the quick
look before (or instead of) loading the JSON into Perfetto.

Prints, from the trace's sim-time track: window count, sim-time span,
events/window and micro-steps/window percentiles, total routed
local/cross split, drops, retransmits, and a coarse events-per-window
sparkline; from the wall-time tracks: total seconds per phase
(trace/compile vs device execute vs harvest/export). With a manifest,
adds the run identity line (config hash, seed, shards, health
verdict) and — when the run sampled flows (--flow-sample) — the flow
summary: sampling accounting, per-lane latency percentiles, and the
hottest (lane, path, kind) latency histogram keys.

Usage: trace_view.py trace.json [--manifest run_manifest.json]
       [--top N]
"""

from __future__ import annotations

import argparse
import json
import sys

SPARK = "▁▂▃▄▅▆▇█"


def _pct(vals, q):
    if not vals:
        return 0.0
    vs = sorted(vals)
    i = min(len(vs) - 1, max(0, round(q / 100 * (len(vs) - 1))))
    return vs[i]


def sparkline(vals, width: int = 60) -> str:
    if not vals:
        return ""
    # bucket to `width` columns, max per bucket (spikes must survive)
    n = len(vals)
    cols = []
    for c in range(min(width, n)):
        lo = c * n // min(width, n)
        hi = max(lo + 1, (c + 1) * n // min(width, n))
        cols.append(max(vals[lo:hi]))
    top = max(cols) or 1
    return "".join(SPARK[min(len(SPARK) - 1,
                             int(v / top * (len(SPARK) - 1)))]
                   for v in cols)


def summarize(trace: dict, manifest: dict | None = None,
              top: int = 5) -> str:
    lines = []
    evs = trace.get("traceEvents", [])
    wins = [e for e in evs if e.get("ph") == "X" and e.get("pid") == 0]
    phases = [e for e in evs if e.get("ph") == "X" and e.get("pid") == 1]
    if manifest:
        h = manifest.get("health", {})
        lines.append(
            f"run {manifest.get('config_hash', '?')[:12]} seed="
            f"{manifest.get('seed')} shards={manifest.get('shards')} "
            f"hosts={manifest.get('num_hosts')} "
            f"verdict={h.get('verdict', 'n/a')}")
    if wins:
        t0 = min(e["ts"] for e in wins)
        t1 = max(e["ts"] + e.get("dur", 0) for e in wins)
        ev = [e.get("args", {}).get("events", 0) for e in wins]
        ms = [e.get("args", {}).get("micro_steps", 0) for e in wins]
        lines.append(
            f"{len(wins)} windows over {(t1 - t0) / 1e6:.3f} sim-s "
            f"({t0 / 1e6:.3f} .. {t1 / 1e6:.3f})")
        lines.append(
            f"events/window p50={_pct(ev, 50)} p90={_pct(ev, 90)} "
            f"p99={_pct(ev, 99)} max={max(ev)}  "
            f"micro-steps/window max={max(ms)}")
        args_sum = {}
        for k in ("routed_local", "routed_cross", "drops", "retx"):
            args_sum[k] = sum(e.get("args", {}).get(k, 0) for e in wins)
        lines.append(
            f"routed local={args_sum['routed_local']} "
            f"cross={args_sum['routed_cross']} "
            f"drops={args_sum['drops']} retx={args_sum['retx']}")
        lines.append("events/window " + sparkline(ev))
        busiest = sorted(wins, key=lambda e: -e.get("args", {})
                         .get("events", 0))[:top]
        for e in busiest:
            a = e.get("args", {})
            lines.append(
                f"  busiest: {e.get('name', '?')} ts={e['ts']:.0f}µs "
                f"events={a.get('events')} "
                f"micro_steps={a.get('micro_steps')} "
                f"qocc_max={a.get('queue_occupancy', {}).get('max')}")
    else:
        lines.append("no sim-time window events in trace")
    if phases:
        totals: dict = {}
        for e in phases:
            # shard=None spans are duplicated per shard tid; count a
            # span once per name+ts so the total is wall time, not
            # wall time x shards
            key = (e.get("name"), e.get("ts"))
            totals.setdefault(key, e.get("dur", 0))
        by_name: dict = {}
        for (name, _), dur in totals.items():
            by_name[name] = by_name.get(name, 0.0) + dur
        lines.append("wall phases: " + "  ".join(
            f"{k}={v / 1e6:.3f}s" for k, v in sorted(by_name.items())))
    if manifest:
        tel = manifest.get("telemetry", {})
        if tel.get("records_lost"):
            lines.append(f"WARNING: {tel['records_lost']} window "
                         f"record(s) lost to ring overrun — trace has "
                         f"gaps")
        fl = manifest.get("flows")
        if fl:
            per = f"1-in-{fl['sample_period']}" \
                if fl.get("sample_period") else "?"
            lines.append(
                f"flows: {fl.get('harvested', 0)} harvested of "
                f"{fl.get('sampled', 0)} sampled ({per} packets), "
                f"lost ring={fl.get('lost_ring', 0)} "
                f"clamp={fl.get('lost_window_clamp', 0)}")
            for lane, s in sorted((fl.get("per_lane") or {}).items(),
                                  key=lambda kv: int(kv[0])):
                lines.append(
                    f"  lane {lane}: {s.get('count', 0)} samples  "
                    f"latency p50={s.get('p50_ns', 0)}ns "
                    f"p95={s.get('p95_ns', 0)}ns "
                    f"p99={s.get('p99_ns', 0)}ns")
            hot = sorted((fl.get("histograms") or {}).items(),
                         key=lambda kv: -kv[1].get("count", 0))[:top]
            for key, s in hot:
                lines.append(
                    f"  hot path {key}: {s.get('count', 0)} samples  "
                    f"p50={s.get('p50_ns', 0)}ns "
                    f"p99={s.get('p99_ns', 0)}ns")
            if fl.get("lost_ring"):
                lines.append(
                    f"WARNING: {fl['lost_ring']} flow record(s) lost "
                    f"to ring overrun — histograms undercount")
        cz = manifest.get("causality")
        if cz:
            lines.append(_window_advance_section(cz, top=top))
        el = manifest.get("elastic")
        if el:
            lines.append(_elastic_section(el, manifest))
    return "\n".join(lines)


def _elastic_section(el: dict, manifest: dict) -> str:
    """The elastic-recovery view of a manifest: initial vs final mesh
    width, every device loss and divergence, and the ladder the
    supervisor walked — the one-screen answer to "how degraded was
    this run, and did it stay verified"."""
    lines = []
    losses = el.get("losses") or []
    divs = el.get("divergences") or []
    steps = el.get("ladder_steps") or []
    trans = el.get("mesh_transitions") or []
    lines.append(
        f"elastic: mesh {el.get('initial_shards')} -> "
        f"{el.get('final_shards')} shard(s), "
        f"{len(losses)} device loss(es), {len(divs)} divergence(s), "
        f"{len(trans)} shrink(s) over {len(steps)} ladder step(s)")
    for ls in losses:
        lines.append(
            f"  DEVICE_LOST shard {ls.get('shard')} "
            f"(attempt {ls.get('attempt')}, mesh {ls.get('mesh')}): "
            f"{ls.get('cause', '?')}")
    for dv in divs:
        lines.append(
            f"  SHARD_DIVERGENCE shard {dv.get('shard')} at "
            f"t={dv.get('tripped_at_ns')}ns (verified through "
            f"{dv.get('verified_through_ns')}ns)")
    for st in steps:
        lines.append(
            f"  ladder: {st.get('action')} {st.get('from')} -> "
            f"{st.get('to')} shard(s) on {st.get('cause')}, resume at "
            f"t={st.get('resume_time_ns')}ns")
    sent = (manifest.get("health") or {}).get("sentinel")
    if sent:
        lines.append(
            f"  sentinel: {sent.get('checks', 0)} barrier check(s), "
            f"{sent.get('trips', 0)} trip(s), verified through "
            f"t={sent.get('verified_through_ns', 0)}ns")
    return "\n".join(lines)


def _window_advance_section(cz: dict, top: int = 5) -> str:
    """The window-advance view of a manifest causality block: how far
    every window jumped (sparkline over the attributed windows, in
    attribution order), WHY each stopped where it did (binding-cause
    table), and how much of the unclamped lookahead the realized jumps
    kept (utilization summary) — the one-screen answer to "is the
    simulator window-bound, and on what"."""
    lines = []
    per = (f"1-in-{cz['sample_period']}"
           if cz.get("sample_period") else "?")
    lines.append(
        f"causality: {cz.get('harvested', 0)} lineage records "
        f"harvested of {cz.get('sampled', 0)} sampled ({per} events), "
        f"lost ring={cz.get('lost_ring', 0)}; "
        f"{cz.get('windows_attributed', 0)} windows attributed "
        f"(lost={cz.get('windows_lost', 0)})")
    jumps = [int(a.get("jump", 0)) for a in (cz.get("advances") or [])]
    if jumps:
        lines.append("window jump ns " + sparkline(jumps))
    causes = cz.get("causes") or {}
    if causes:
        total = sum(causes.values()) or 1
        lines.append("binding cause:")
        for name, n in sorted(causes.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {name:<16} {n:>8}  "
                         f"({n * 100 // total}%)")
    edges = cz.get("edges") or {}
    for key, n in sorted(edges.items(), key=lambda kv: -kv[1])[:top]:
        lines.append(f"  binding edge {key}: {n} windows")
    ju = cz.get("jump_utilization_pct") or {}
    if ju:
        lines.append(
            f"lookahead utilization p50={ju.get('p50', 0)}% "
            f"p95={ju.get('p95', 0)}% p99={ju.get('p99', 0)}% "
            f"mean={ju.get('mean', 0)}% (realized jump / unclamped "
            f"lookahead)")
    il = cz.get("idle_lane_pct") or {}
    if il:
        lines.append(
            f"idle lanes at barrier p50={il.get('p50', 0)}% "
            f"p95={il.get('p95', 0)}% p99={il.get('p99', 0)}%")
    for i, ch in enumerate((cz.get("chains") or [])[:top]):
        lines.append(
            f"  chain {i}: {ch.get('length', 0)} events over "
            f"{ch.get('span_ns', 0)}ns across "
            f"{ch.get('hosts', 0)} host(s)")
    if cz.get("lost_ring"):
        lines.append(
            f"WARNING: {cz['lost_ring']} lineage record(s) lost to "
            f"ring overrun — chains may be truncated")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a shadow-tpu telemetry trace")
    ap.add_argument("trace", help="Chrome-trace JSON (--trace-out)")
    ap.add_argument("--manifest", default=None,
                    help="run_manifest.json for the identity line")
    ap.add_argument("--top", type=int, default=5,
                    help="busiest windows to list")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        trace = json.load(f)
    manifest = None
    if args.manifest:
        with open(args.manifest) as f:
            manifest = json.load(f)
    print(summarize(trace, manifest, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
