#!/usr/bin/env python3
"""Dual-mode conformance driver — run vproc workloads on BOTH
backends (simulation + real host kernel) and diff the normalized
syscall traces, or compare two previously dumped traces offline.

Run mode (executes both backends per workload):
    dualmode_diff.py --workload bind --workload epoll
    dualmode_diff.py --workload fast          # every fast workload
    dualmode_diff.py --workload all           # incl. slow ones
Compare mode (offline, no execution):
    dualmode_diff.py --sim sim.json --host host.json
Common:
    --seed N --time-scale F --json report.json --dump-dir DIR --list

Exit codes: 0 = all agree, 1 = usage/IO error, 2 = sandbox has no
bindable localhost ports (environment, not divergence), 4 = at least
one workload diverged or errored (matches the CLI's divergence code).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

EXIT_OK = 0
EXIT_USAGE = 1
EXIT_NO_PORTS = 2
EXIT_DIVERGED = 4


def _expand(names, catalog, fast, full):
    out = []
    for n in names:
        if n == "all":
            out.extend(full)
        elif n == "fast":
            out.extend(fast)
        elif n in catalog:
            out.append(n)
        else:
            return None, n
    # de-dup, keep first-mention order
    return list(dict.fromkeys(out)), None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run workloads under both backends and diff "
                    "normalized syscall traces (docs/7-conformance.md)")
    ap.add_argument("--workload", action="append", default=[],
                    help="catalog name, or 'fast'/'all' (repeatable)")
    ap.add_argument("--sim", default=None,
                    help="compare mode: dumped sim trace JSON")
    ap.add_argument("--host", default=None,
                    help="compare mode: dumped host trace JSON")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--time-scale", type=float, default=0.05,
                    help="sim ns -> real seconds on the host backend")
    ap.add_argument("--json", default=None, help="write a JSON report")
    ap.add_argument("--dump-dir", default=None,
                    help="dump each run's normalized traces here")
    ap.add_argument("--list", action="store_true",
                    help="list the workload catalog and exit")
    args = ap.parse_args(argv)

    from shadow_tpu.hostrun import (
        DUAL_WORKLOADS, FAST_DUAL_WORKLOADS, WORKLOADS, PortsUnavailable,
        diff_traces, render, run_dual)
    from shadow_tpu.hostrun.trace import load as load_trace

    if args.list:
        for w in WORKLOADS.values():
            mode = "dual" if w.host_ok else "sim-only"
            tag = " [slow]" if w.slow else ""
            note = f" — {w.note}" if w.note else ""
            print(f"{w.name:18s} {mode}{tag}{note}")
        return EXIT_OK

    if (args.sim is None) != (args.host is None):
        print("compare mode needs BOTH --sim and --host",
              file=sys.stderr)
        return EXIT_USAGE

    report = {"mode": None, "results": {}}
    worst = EXIT_OK

    if args.sim is not None:
        report["mode"] = "compare"
        try:
            sim_doc = load_trace(args.sim)
            host_doc = load_trace(args.host)
        except (OSError, ValueError) as e:
            print(f"ERROR: {e}", file=sys.stderr)
            return EXIT_USAGE
        res = diff_traces(sim_doc.get("procs", {}),
                          host_doc.get("procs", {}))
        print(render(res))
        report["results"]["compare"] = res.to_json()
        if not res.agree:
            worst = EXIT_DIVERGED
    else:
        names, bad = _expand(args.workload or ["fast"], WORKLOADS,
                             FAST_DUAL_WORKLOADS, DUAL_WORKLOADS)
        if names is None:
            print(f"unknown workload {bad!r} (try --list)",
                  file=sys.stderr)
            return EXIT_USAGE
        report["mode"] = "run"
        for name in names:
            w = WORKLOADS[name]
            if not w.host_ok:
                print(f"== {name}: SKIP (sim-only: {w.note})")
                report["results"][name] = {"agree": None,
                                           "skipped": "sim-only"}
                continue
            try:
                res = run_dual(name, seed=args.seed,
                               time_scale=args.time_scale)
            except PortsUnavailable as e:
                print(f"== {name}: SKIP (no localhost ports: {e})",
                      file=sys.stderr)
                return EXIT_NO_PORTS
            except Exception as e:  # noqa: BLE001 — a verdict, reported
                print(f"== {name}: ERROR {type(e).__name__}: {e}",
                      file=sys.stderr)
                report["results"][name] = {
                    "agree": False,
                    "error": f"{type(e).__name__}: {e}"}
                worst = EXIT_DIVERGED
                continue
            print(f"== {name}")
            print(render(res.diff))
            report["results"][name] = res.diff.to_json()
            if not res.diff.agree:
                worst = EXIT_DIVERGED
            if args.dump_dir:
                os.makedirs(args.dump_dir, exist_ok=True)
                for side, procs in (("sim", res.sim), ("host", res.host)):
                    path = os.path.join(args.dump_dir,
                                        f"{name}.{side}.json")
                    with open(path, "w") as f:
                        json.dump({"meta": {"workload": name,
                                            "backend": side,
                                            "seed": args.seed},
                                   "procs": procs}, f, indent=1,
                                  sort_keys=True)

    report["agree"] = worst == EXIT_OK
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
    return worst


if __name__ == "__main__":
    sys.exit(main())
