#!/usr/bin/env python3
"""Speed-of-light analysis of a run manifest — "how fast COULD this
run have gone, and what is in the way".

Reads a run_manifest.json whose run sampled causality
(--causality-sample; telemetry/causality.py) and derives three lower
bounds on wallclock from measured per-unit costs:

- **dispatch floor**: dispatches x measured per-dispatch wall cost.
  The windowed-PDES tax — every barrier costs one host round trip, so
  fewer/larger windows (chunking, adaptive jump) shrink this floor.
- **window floor**: windows x the best-case per-window device cost
  (derived from the device-execute phase over the windows that ran).
  This is the conservative-synchronization cost of the window count
  the binding constraints produced.
- **chain floor**: longest critical chain length x measured per-event
  cost. Causally-serialized events cannot be batched into one window
  pass no matter how windows are sized — the hard serial residue.

The report names the binding constraint per window cohort (windows
grouped by their latched binding cause), the top reasons the run sits
above its speed-of-light, and the levers that attack each one. Exits
non-zero when the manifest is unusable, zero otherwise (the report is
an analysis, not a gate).

Usage: critpath.py run_manifest.json [--json] [--top N]
"""

from __future__ import annotations

import argparse
import json
import sys

# lever text per binding cause: what an operator does about a cohort
# of windows bound by this constraint (telemetry/causality.py
# CAUSE_NAMES order)
_LEVERS = {
    "min_jump_floor": "raise the topology's minimum latency edge or "
                      "--runahead (the static floor IS the window "
                      "size); --adaptive-jump lets fault plans that "
                      "raise latencies grow windows past it",
    "adaptive_edge": "the live latency table's minimum edge binds — "
                     "co-locate or slow the named vertex pair, or "
                     "shard so the binding edge stays shard-local",
    "fault_record": "windows clamp to fault-plan record times — "
                    "coalesce fault records or batch them away from "
                    "the hot window range",
    "inject_horizon": "windows clamp to the injection staging "
                      "horizon — raise --inject-lanes (deeper "
                      "staging) or pre-sort the trace so refills "
                      "cover longer spans",
    "end_time": "windows clamp to end_time (run tail) — benign",
}


def _get(d: dict, *keys, default=None):
    cur = d
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default
        cur = cur[k]
    return cur


def analyze(man: dict, top: int = 3) -> dict:
    """The speed-of-light report object for one run manifest."""
    cz = man.get("causality")
    if not isinstance(cz, dict):
        raise ValueError(
            'manifest has no "causality" block — run with '
            "--causality-sample N (tools/critpath.py reads the "
            "lineage/attribution planes it produces)")
    ctr = man.get("counters") or {}
    windows = int(ctr.get("windows", 0) or 0)
    events = int(ctr.get("events_processed", 0) or 0)
    wall = man.get("wall_seconds")
    phases = man.get("wall_phases_s") or {}
    # device time: prefer the execute phase (excludes trace/compile);
    # fall back to total wall minus compile-ish phases, then to wall
    device_s = None
    for k in ("device-execute", "supervised-run", "window-loop"):
        if isinstance(phases.get(k), (int, float)):
            device_s = float(phases[k])
            break
    if device_s is None and isinstance(wall, (int, float)):
        device_s = float(wall)

    disp = man.get("dispatch") or {}
    dispatches = int(disp.get("dispatches", 0) or 0)
    if not dispatches and windows:
        wpd = max(1, int(disp.get("windows_per_dispatch", 1) or 1))
        dispatches = (windows + wpd - 1) // wpd

    report: dict = {
        "windows": windows,
        "events": events,
        "wall_seconds": wall,
        "device_seconds": device_s,
    }

    # measured unit costs — these make the floors empirical, not
    # guesses: the run's own realized cost per dispatch / window /
    # event is the best available "speed of light" for THIS program
    # on THIS backend
    per_dispatch_s = (device_s / dispatches
                      if device_s and dispatches else None)
    per_window_s = device_s / windows if device_s and windows else None
    per_event_s = device_s / events if device_s and events else None
    chains = cz.get("chains") or []
    chain_len = max((int(c.get("length", 0) or 0) for c in chains),
                    default=0)

    floors: dict = {}
    if per_dispatch_s is not None:
        floors["dispatch_floor_s"] = round(
            dispatches * per_dispatch_s, 6)
    if per_window_s is not None:
        floors["window_floor_s"] = round(windows * per_window_s, 6)
    if per_event_s is not None and chain_len:
        # the chain is sampled at 1-in-P: a sampled chain of length L
        # witnesses >= L causally-serialized executions
        floors["chain_floor_s"] = round(chain_len * per_event_s, 9)
    report["unit_costs"] = {
        k: v for k, v in (("per_dispatch_s", per_dispatch_s),
                          ("per_window_s", per_window_s),
                          ("per_event_s", per_event_s))
        if v is not None}
    report["floors"] = floors
    report["critical_chain_len"] = chain_len

    # window cohorts by binding cause: each cohort's share of the
    # window count is its share of the window floor — the attribution
    # that turns "too many windows" into "THESE constraints made them"
    causes = cz.get("causes") or {}
    attributed = int(cz.get("windows_attributed", 0) or 0)
    cohorts = []
    for name, n in sorted(causes.items(), key=lambda kv: -kv[1]):
        c: dict = {"cause": name, "windows": int(n)}
        if attributed:
            c["share_pct"] = int(n) * 100 // attributed
        if per_window_s is not None:
            c["floor_s"] = round(int(n) * per_window_s, 6)
        if name in _LEVERS:
            c["lever"] = _LEVERS[name]
        cohorts.append(c)
    report["window_cohorts"] = cohorts

    # top reasons the run sits above its floors, ranked: dominant
    # binding cause first, then low lookahead utilization, then idle
    # lanes — each names its evidence and its lever
    reasons = []
    if cohorts:
        lead = cohorts[0]
        reasons.append({
            "reason": f"windows bound by {lead['cause']}",
            "evidence": f"{lead['windows']} of {attributed} "
                        f"attributed window(s) "
                        f"({lead.get('share_pct', 0)}%)",
            "lever": lead.get("lever", ""),
        })
    ju = cz.get("jump_utilization_pct") or {}
    if isinstance(ju.get("p50"), int) and ju["p50"] < 100:
        reasons.append({
            "reason": "realized jumps below the available lookahead",
            "evidence": f"jump utilization p50={ju['p50']}% "
                        f"p95={ju.get('p95')}% — clamps (fault "
                        f"records, injection horizon, end time) "
                        f"shrink windows the latency tables would "
                        f"allow",
            "lever": "remove or batch the clamping constraint named "
                     "by the cohort table",
        })
    il = cz.get("idle_lane_pct") or {}
    if isinstance(il.get("p50"), int) and il["p50"] > 0:
        reasons.append({
            "reason": "idle lanes at the window barrier",
            "evidence": f"idle-lane fraction p50={il['p50']}% "
                        f"p95={il.get('p95')}% — the global window "
                        f"waits on its busiest host while these sit "
                        f"idle",
            "lever": "rebalance load across hosts, or pack more "
                     "tenants per program (fleet packed jobs) so "
                     "idle rows do someone's work",
        })
    edges = cz.get("edges") or {}
    if edges:
        (ek, en), = sorted(edges.items(), key=lambda kv: -kv[1])[:1]
        reasons.append({
            "reason": f"latency edge {ek} repeatedly binds the "
                      f"adaptive window",
            "evidence": f"{en} window(s) sized by {ek}",
            "lever": _LEVERS["adaptive_edge"],
        })
    if chain_len and windows and chain_len >= windows:
        reasons.append({
            "reason": "causally-serialized event chain spans the run",
            "evidence": f"critical chain of {chain_len} event(s) vs "
                        f"{windows} window(s) — at least one event "
                        f"per window is forced serial",
            "lever": "this is the hard serial residue — only a "
                     "faster per-event step (kernel work) attacks it",
        })
    report["reasons"] = reasons[:top]

    # headroom: measured device time over the tightest floor
    best = max(floors.values(), default=None)
    if best and device_s:
        report["headroom_pct"] = max(
            0, round((device_s - best) * 100.0 / device_s, 1))
    return report


def render(report: dict) -> str:
    lines = []
    w = report.get("windows")
    lines.append(
        f"run: {w} window(s), {report.get('events')} event(s), "
        f"device {report.get('device_seconds')}s "
        f"(wall {report.get('wall_seconds')}s)")
    fl = report.get("floors") or {}
    if fl:
        lines.append("speed-of-light floors: " + "  ".join(
            f"{k}={v}s" for k, v in sorted(fl.items())))
    if report.get("headroom_pct") is not None:
        lines.append(f"headroom above tightest floor: "
                     f"{report['headroom_pct']}%")
    coh = report.get("window_cohorts") or []
    if coh:
        lines.append("window cohorts (binding constraint -> windows):")
        for c in coh:
            lines.append(
                f"  {c['cause']:<16} {c['windows']:>8} window(s) "
                f"({c.get('share_pct', 0)}%)"
                + (f"  floor {c['floor_s']}s" if "floor_s" in c
                   else ""))
    if report.get("critical_chain_len"):
        lines.append(f"longest sampled critical chain: "
                     f"{report['critical_chain_len']} event(s)")
    for i, r in enumerate(report.get("reasons") or [], 1):
        lines.append(f"reason {i}: {r['reason']}")
        lines.append(f"  evidence: {r['evidence']}")
        if r.get("lever"):
            lines.append(f"  lever: {r['lever']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="speed-of-light analysis of a causality-traced "
                    "run manifest")
    ap.add_argument("manifest", help="run_manifest.json path")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as JSON instead of text")
    ap.add_argument("--top", type=int, default=3,
                    help="reasons to rank (default 3)")
    args = ap.parse_args(argv)
    try:
        with open(args.manifest) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        print(f"error: {args.manifest}: {e}", file=sys.stderr)
        return 1
    try:
        report = analyze(man, top=args.top)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, indent=1, sort_keys=True)
          if args.json else render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
