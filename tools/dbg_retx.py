import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from shadow_tpu.apps import bulk
from shadow_tpu.core import simtime
from shadow_tpu.net.build import HostSpec, build, run
from shadow_tpu.net.state import NetConfig
from shadow_tpu.net import tcp as tcpmod

GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="lat" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="up" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="dn" />
  <key attr.name="type" attr.type="string" for="node" id="ty" />
  <graph edgedefault="undirected">
    <node id="west"><data key="up">10240</data><data key="dn">10240</data>
      <data key="ty">client</data></node>
    <node id="east"><data key="up">10240</data><data key="dn">10240</data>
      <data key="ty">server</data></node>
    <edge source="west" target="west"><data key="lat">5.0</data></edge>
    <edge source="west" target="east"><data key="lat">25.0</data></edge>
    <edge source="east" target="east"><data key="lat">5.0</data></edge>
  </graph>
</graphml>"""

total = 100_000
cfg = NetConfig(num_hosts=2, end_time=30 * simtime.ONE_SECOND, seed=1)
hosts = [HostSpec(name="client", type="client", proc_start_time=simtime.ONE_SECOND),
         HostSpec(name="server", type="server")]
b = build(cfg, GRAPH, hosts)
client = jnp.asarray(np.arange(2) == b.host_of("client"))
server = jnp.asarray(np.arange(2) == b.host_of("server"))
b.sim = bulk.setup(b.sim, client_mask=client, server_mask=server,
                   server_ip=b.ip_of("server"), server_port=8080,
                   total_bytes=total)

# instrument: wrap _retransmit_one to print when a retransmit happens
orig = tcpmod._retransmit_one
def traced(cfg2, sim, mask, slot, now, buf):
    if bool(jnp.any(mask)):
        lanes = np.nonzero(np.asarray(mask))[0]
        for h in lanes:
            print(f"RETX at t={int(now[h])/1e6:.3f}ms lane={h} slot={int(slot[h])} "
                  f"una={int(sim.tcp.snd_una[h, int(slot[h])])} "
                  f"nxt={int(sim.tcp.snd_nxt[h, int(slot[h])])} "
                  f"max={int(sim.tcp.snd_max[h, int(slot[h])])} "
                  f"end={int(sim.tcp.snd_end[h, int(slot[h])])} "
                  f"st={int(sim.tcp.st[h, int(slot[h])])} "
                  f"dup={int(sim.tcp.dup_acks[h, int(slot[h])])} "
                  f"rto={int(sim.tcp.rto_ms[h, int(slot[h])])}")
    return orig(cfg2, sim, mask, slot, now, buf)
tcpmod._retransmit_one = traced

with jax.disable_jit():
    sim, stats = run(b, app_handlers=(bulk.handler,))
print("retx:", np.asarray(sim.tcp.retx_segs), "rcvd:", np.asarray(sim.app.rcvd))
print("st:", np.asarray(sim.tcp.st))
