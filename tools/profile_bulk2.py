"""Prefix-bisect net/bulk.py's bulk_fn: re-create its body with a
cut-point argument; time each prefix. The returned value folds every
live intermediate into a scalar so XLA cannot dead-code-eliminate the
prefix under test."""

from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "tpu,cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from shadow_tpu.core import rng, simtime
from shadow_tpu.core.events import EventKind, _tie_key
from shadow_tpu.net import bulk as bulkmod
from shadow_tpu.net import packetfmt as pf
from shadow_tpu.net.state import TB_REFILL_INTERVAL, host_of_ip

I32 = jnp.int32
I64 = jnp.int64


from tools.perfutil import timeit  # noqa: E402


def make_prefix(cfg, app_bulk, wend, stop):
    """bulk_fn body up to stage `stop`; returns a scalar folding all
    live values."""

    def fn(sim):
        acc = jnp.zeros((), I64)

        def fold(*xs):
            nonlocal acc
            for x in xs:
                acc = acc + jnp.sum(x).astype(I64)

        net = sim.net
        q = sim.events
        H, K = q.time.shape
        GH = net.host_ip.shape[0]
        lane = net.lane_id

        t = q.time
        inwin = t < jnp.asarray(wend, simtime.DTYPE)
        tie = _tie_key(q.src, q.seq)
        length = q.words[:, :, pf.W_LEN]
        wl_all = pf.wire_length(
            jnp.full((H, K), pf.PROTO_UDP, I32), length).astype(I64)
        wl = jnp.where(inwin, wl_all, 0)
        nonboot = t >= cfg.bootstrap_end
        app_ok = app_bulk.precheck(cfg, sim)
        sndbuf_ok = jnp.min(net.sk_sndbuf, axis=1) > app_bulk.max_send_len
        if stop == "head":
            fold(wl, nonboot, app_ok, sndbuf_ok)
            return acc

        src = q.src
        pw = q.words[:, :, pf.W_PORTS]
        src_port = pw & 0xFFFF
        dst_port = (pw >> 16) & 0xFFFF
        dst_ip = q.words[:, :, pf.W_DSTIP].astype(jnp.uint32).astype(I64)
        src_ip = net.host_ip[jnp.clip(src, 0, GH - 1)]
        payref = q.words[:, :, pf.W_PAYREF]
        slot = bulkmod._lookup_bulk(net, inwin, dst_ip, dst_port, src_ip,
                                    src_port)
        rcvbuf_at = bulkmod._gather_hs_bulk(net.sk_rcvbuf, slot)
        rcv_fit = jnp.all(~inwin | (slot < 0) | (length <= rcvbuf_at), axis=1)
        if stop == "lookup":
            fold(slot, rcv_fit)
            return acc

        elig = bulkmod._eligibility(cfg, sim, inwin, t, wl, nonboot,
                                    app_ok & sndbuf_ok & rcv_fit)
        ev = inwin & elig[:, None]
        n_ev = jnp.sum(ev, axis=1, dtype=I32)
        order = bulkmod.make_order(t, tie)
        matched = ev & (slot >= 0)
        nosock = ev & (slot < 0)
        S = net.sk_type.shape[1]
        arr_per_sock = jnp.sum(
            matched[:, :, None]
            & (slot[:, :, None] == jnp.arange(S)[None, None, :]),
            axis=1, dtype=I32)
        if stop == "elig":
            fold(elig, n_ev, arr_per_sock,
                 order.perm if order.perm is not None else order.prec)
            return acc

        d = bulkmod.BulkDeliveries(
            mask=matched, time=t, tie=tie, order=order, slot=slot,
            src_ip=src_ip, src_port=src_port, length=length, payref=payref)
        sim2, sends = app_bulk.run(cfg, sim, d)
        net = sim2.net
        smask = sends.mask & elig[:, None]
        sport = bulkmod._gather_hs_bulk(net.sk_bound_port, sends.slot)
        send_per_sock = jnp.sum(
            smask[:, :, None]
            & (sends.slot[:, :, None] == jnp.arange(S)[None, None, :]),
            axis=1, dtype=I32)
        n_send = jnp.sum(smask, axis=1, dtype=I32)
        if stop == "app":
            fold(smask, sport, send_per_sock, n_send)
            return acc

        dsth = jnp.where(sends.dst_host >= 0, sends.dst_host,
                         host_of_ip(net, sends.dst_ip))
        known = smask & (dsth >= 0)
        u2 = rng.uniform_at(net.rng_keys, sends.nic_draw_ctr)
        V = net.latency_ns.shape[0]
        if V == 1:
            rel = net.reliability[0, 0]
            lat = net.latency_ns[0, 0]
        else:
            vsrc = net.vertex_of_host[lane][:, None]
            vdst = net.vertex_of_host[jnp.clip(dsth, 0, GH - 1)]
            rel = net.reliability[vsrc, vdst]
            lat = net.latency_ns[vsrc, vdst]
        drop = known & nonboot & (sends.length > 0) & (u2 > rel)
        emit_ok = known & ~drop
        if stop == "nic":
            fold(emit_ok, drop)
            return acc

        nosock_status = (
            q.words[:, :, pf.W_STATUS]
            | pf.PDS_ROUTER_ENQUEUED | pf.PDS_ROUTER_DEQUEUED
            | pf.PDS_RCV_INTERFACE_RECEIVED | pf.PDS_RCV_SOCKET_DROPPED)
        reply_drop_status = jnp.full(
            (H, K), pf.PDS_SND_CREATED | pf.PDS_SND_SOCKET_BUFFERED
            | pf.PDS_SND_INTERFACE_SENT | pf.PDS_INET_DROPPED, I32)
        drop_any = nosock | drop
        drop_status = jnp.where(nosock, nosock_status, reply_drop_status)
        n_drop = jnp.sum(drop_any, axis=1, dtype=I32)
        drop_rank = bulkmod.rank_in_order(order, drop_any)
        last_col = drop_any & (drop_rank == (n_drop[:, None] - 1))
        picked_drop = jnp.sum(jnp.where(last_col, drop_status, 0), axis=1,
                              dtype=I32)
        new_last_drop = jnp.where(elig & (n_drop > 0), picked_drop,
                                  net.last_drop_status)
        swl = jnp.where(smask, pf.wire_length(
            jnp.full((H, K), pf.PROTO_UDP, I32), sends.length), 0).astype(I64)
        if stop == "audit":
            fold(new_last_drop, swl)
            return acc

        qq = jnp.where(ev, t // TB_REFILL_INTERVAL, 0)
        q_last = jnp.maximum(jnp.max(qq, axis=1), net.tb_quantum)
        q_last = jnp.where(n_ev > 0, q_last, net.tb_quantum)
        qv = jnp.where(ev, qq, q_last[:, None])
        w_recv = jnp.where(nonboot, wl, 0)
        w_send = jnp.where(nonboot & smask, swl, 0)
        suff_recv = bulkmod.suffix_sum(order, w_recv)
        suff_send = bulkmod.suffix_sum(order, w_send)
        cap_r = net.tb_recv_refill + pf.MTU
        cap_s = net.tb_send_refill + pf.MTU
        big = jnp.iinfo(jnp.int64).max // 2
        dq_total = (q_last - net.tb_quantum)

        def bucket_final(s0, cap, refill, w, suffw):
            straight = s0 + dq_total * refill - jnp.sum(w, axis=1)
            clamp = jnp.where(
                ev,
                cap[:, None] - w + (q_last[:, None] - qv) * refill[:, None]
                - suffw, big)
            return jnp.minimum(straight, jnp.min(clamp, axis=1))

        new_recv_tok = bucket_final(net.tb_recv_tokens, cap_r,
                                    net.tb_recv_refill, w_recv, suff_recv)
        new_send_tok = bucket_final(net.tb_send_tokens, cap_s,
                                    net.tb_send_refill, w_send, suff_send)
        if stop == "bucket":
            fold(new_recv_tok, new_send_tok)
            return acc

        ord_col = bulkmod.rank_in_order(order, ev)
        send_rank = bulkmod.rank_in_order(order, emit_ok)
        seq = q.next_seq[:, None] + send_rank
        M = sim.outbox.capacity
        lane_h = jnp.arange(H)[:, None]
        col = jnp.where(emit_ok, ord_col, M)

        def place(val, fill, dtype):
            base = jnp.full((H, M), fill, dtype)
            return base.at[lane_h, col].set(jnp.asarray(val, dtype),
                                            mode="drop")

        got_col = jnp.zeros((H, M), bool).at[lane_h, col].set(
            True, mode="drop")
        o_dst = place(dsth, -1, I32)
        o_time = place(t + lat, simtime.INVALID, I64)
        o_src = place(jnp.broadcast_to(lane[:, None], (H, K)), 0, I32)
        o_seq = place(seq, 0, I32)
        o_kind = jnp.where(got_col, EventKind.PACKET, 0).astype(I32)
        if stop == "place":
            fold(got_col, o_dst, o_time, o_src, o_seq, o_kind)
            return acc

        wds = jnp.zeros((H, K, q.words.shape[2]), I32)
        wds = wds.at[:, :, pf.W_PROTO].set(pf.PROTO_UDP)
        wds = wds.at[:, :, pf.W_LEN].set(sends.length)
        wds = wds.at[:, :, pf.W_PORTS].set(pf.pack_ports(sport, sends.dst_port))
        wds = wds.at[:, :, pf.W_PAYREF].set(sends.payref)
        wds = wds.at[:, :, pf.W_DSTIP].set(
            sends.dst_ip.astype(jnp.uint32).astype(I32))
        wds = wds.at[:, :, pf.W_STATUS].set(
            pf.PDS_SND_CREATED | pf.PDS_SND_SOCKET_BUFFERED
            | pf.PDS_SND_INTERFACE_SENT | pf.PDS_INET_SENT)
        o_words = jnp.zeros((H, M, q.words.shape[2]), I32).at[
            lane_h, col].set(wds, mode="drop")
        if stop == "words":
            fold(got_col, o_dst, o_time, o_src, o_seq, o_kind, o_words)
            return acc
        raise ValueError(stop)

    return fn


def main():
    H = int(os.environ.get("PB_HOSTS", "10240"))
    load = int(os.environ.get("PB_LOAD", "8"))
    print(f"backend: {jax.default_backend()}  H={H}")

    from shadow_tpu.apps import phold
    from tools.perfutil import build_warm_phold

    w = build_warm_phold(H, load)
    b, sim, wstart = w["bundle"], w["sim"], w["wstart"]
    cfg, bulk_fn = b.cfg, w["bulk_fn"]
    wend = int(wstart) + b.min_jump

    prev = 0.0
    for stage in ["head", "lookup", "elig", "app", "nic", "audit",
                  "bucket", "place", "words"]:
        fn = jax.jit(make_prefix(cfg, phold.BULK, wend, stage))
        t = timeit(fn, sim)
        print(f"prefix {stage:8s}: {t*1e3:8.2f} ms  (+{(t-prev)*1e3:7.2f})")
        prev = t

    bj = jax.jit(lambda s: bulk_fn(s, wend))
    print(f"full bulk_fn   : {timeit(bj, sim)*1e3:8.2f} ms")


if __name__ == "__main__":
    main()
