#!/usr/bin/env python3
"""Offline fault-plan validator — CI gate for fault schedules before
they burn a run (the determinism contract makes a bad plan fail the
same way every retry, so catch it before the cluster does).

Checks (faults/plan.py validate_records): times sorted and
non-negative, kinds known, link kinds carry both endpoints, host /
vertex ids in range when bounds are given, loss in [0,1],
latency deltas non-negative (a negative delta would break the
conservative window), crash-before-restart ordering per host; warns
when times do not align to the window length (effects quantize to the
enclosing window boundary).

Inputs: a standalone JSON plan ({"faults": [...]}; see
examples/faultplan_degraded.json) or a shadow.config.xml whose
<fault> elements are checked by name only (name->index resolution
needs a built topology; use --hosts/--vertices for range checks on
raw-integer plans).

With --checkpoint the plan is additionally cross-checked against a
snapshot's recorded metadata (utils/checkpoint.py peek_meta): the
snapshot's num_hosts feeds the range checks, and any target capacity
flag (--event-capacity / --outbox-capacity / --router-ring) smaller
than what the snapshot was saved at is an ERROR — resuming into a
shrunken config cannot transplant (capacities only grow), so it fails
here at lint time instead of at resume time.

Usage: faultplan_lint.py plan.json [--hosts N] [--vertices N]
       [--min-jump-ns NS] [--checkpoint SNAP.npz]
       [--event-capacity N] [--outbox-capacity N] [--router-ring N]
Exit 0 = clean (warnings allowed), 1 = errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lint_text(text: str, *, hosts=None, vertices=None, min_jump_ns=None):
    """Returns (errors, warnings) for a JSON plan or XML config blob."""
    from shadow_tpu.faults.plan import (FaultRecord, KIND_NAMES,
                                        records_from_json,
                                        validate_records, _value_raw)

    stripped = text.lstrip()
    if stripped.startswith("<"):
        from shadow_tpu.config.xmlconfig import parse_config

        cfg = parse_config(text)
        recs = []
        errors = []
        names = {name for name, _ in cfg.expanded_hosts()}
        # Name -> index resolution needs placement; lint with stable
        # symbolic indices so per-host ordering checks (crash before
        # restart) still see distinct endpoints. Range checks are
        # skipped for names (a configured name is in range by
        # construction).
        sym_idx: dict = {}

        def sym(tok):
            return sym_idx.setdefault(str(tok), len(sym_idx))

        for i, spec in enumerate(cfg.faults):
            kname = spec.kind.lower()
            if kname not in KIND_NAMES:
                errors.append(f"<fault> {i} (t={spec.time_ns}): unknown "
                              f"kind '{spec.kind}'")
                continue
            for end in (spec.a, spec.b):
                if end is not None and end not in names:
                    try:
                        int(end)
                    except (TypeError, ValueError):
                        errors.append(
                            f"<fault> {i} (t={spec.time_ns}): '{end}' "
                            f"names no configured host")
            kind = KIND_NAMES[kname]
            recs.append(FaultRecord(
                t_ns=spec.time_ns, kind=kind,
                a=sym(spec.a), b=sym(spec.b) if spec.b is not None else -1,
                value=_value_raw(kind, spec.value)))
        e2, warnings = validate_records(recs, min_jump_ns=min_jump_ns)
        return errors + e2, warnings
    try:
        recs = records_from_json(json.loads(text))
    except (ValueError, KeyError) as e:
        return [str(e)], []
    return validate_records(recs, num_hosts=hosts, num_vertices=vertices,
                            min_jump_ns=min_jump_ns)


def lint_against_checkpoint(meta: dict, *, hosts=None,
                            event_capacity=None, outbox_capacity=None,
                            router_ring=None):
    """Cross-check resume intent against a snapshot's __meta__.
    Returns (errors, warnings, effective_hosts) — effective_hosts is
    the snapshot's num_hosts, for the plan's range checks."""
    errors: list = []
    warnings: list = []
    caps = meta.get("capacities") or {}
    snap_hosts = caps.get("num_hosts")
    if hosts is not None and snap_hosts is not None \
            and hosts != snap_hosts:
        errors.append(
            f"--hosts {hosts} but the snapshot was saved with "
            f"num_hosts={snap_hosts} — a transplant cannot change "
            f"the host axis")
    targets = {"event_capacity": event_capacity,
               "outbox_capacity": outbox_capacity,
               "router_ring": router_ring}
    for knob, want in targets.items():
        have = caps.get(knob)
        if want is None or have is None:
            continue
        if want < have:
            errors.append(
                f"--{knob.replace('_', '-')} {want} is smaller than "
                f"the snapshot's recorded {knob}={have} — capacities "
                f"only grow; resuming into a shrunken config would "
                f"be refused at load time")
        elif want > have:
            warnings.append(
                f"--{knob.replace('_', '-')} {want} grows the "
                f"snapshot's {knob}={have}; the resume will "
                f"transplant (pad-with-empty)")
    if meta.get("shards") is not None:
        warnings.append(
            f"snapshot was taken under {meta['shards']} shard(s); "
            f"state is global-layout, any --workers count resumes it")
    return errors, warnings, (snap_hosts if hosts is None else hosts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a fault plan offline (JSON plan or "
                    "shadow.config.xml)")
    ap.add_argument("plan", help="plan file (.json) or config (.xml)")
    ap.add_argument("--hosts", type=int, default=None,
                    help="host count for crash/restart range checks")
    ap.add_argument("--vertices", type=int, default=None,
                    help="topology vertex count for link/partition "
                         "range checks")
    ap.add_argument("--min-jump-ns", type=int, default=None,
                    help="window length: warn on times that quantize")
    ap.add_argument("--checkpoint", default=None, metavar="SNAP",
                    help="cross-check against a snapshot's recorded "
                         "capacity/shard metadata (resume lint)")
    ap.add_argument("--event-capacity", type=int, default=None,
                    help="intended resume event_capacity (checked "
                         "against the snapshot's)")
    ap.add_argument("--outbox-capacity", type=int, default=None)
    ap.add_argument("--router-ring", type=int, default=None)
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress warnings, print errors only")
    args = ap.parse_args(argv)

    with open(args.plan) as f:
        text = f.read()
    hosts = args.hosts
    ckpt_errors: list = []
    ckpt_warnings: list = []
    if args.checkpoint:
        from shadow_tpu.utils.checkpoint import peek_meta

        try:
            meta = peek_meta(args.checkpoint)
        except (OSError, ValueError, KeyError) as e:
            ckpt_errors.append(f"{args.checkpoint}: {e}")
            meta = None
        if meta is not None:
            ckpt_errors, ckpt_warnings, hosts = lint_against_checkpoint(
                meta, hosts=args.hosts,
                event_capacity=args.event_capacity,
                outbox_capacity=args.outbox_capacity,
                router_ring=args.router_ring)
    errors, warnings = lint_text(text, hosts=hosts,
                                 vertices=args.vertices,
                                 min_jump_ns=args.min_jump_ns)
    errors = ckpt_errors + errors
    warnings = ckpt_warnings + warnings
    for e in errors:
        print(f"ERROR: {e}", file=sys.stderr)
    if not args.quiet:
        for w in warnings:
            print(f"WARNING: {w}", file=sys.stderr)
    if errors:
        print(f"{args.plan}: {len(errors)} error(s), "
              f"{len(warnings)} warning(s)", file=sys.stderr)
        return 1
    print(f"{args.plan}: OK ({len(warnings)} warning(s))",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
