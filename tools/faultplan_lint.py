#!/usr/bin/env python3
"""Offline fault-plan validator — CI gate for fault schedules before
they burn a run (the determinism contract makes a bad plan fail the
same way every retry, so catch it before the cluster does).

Checks (faults/plan.py validate_records): times sorted and
non-negative, kinds known, link kinds carry both endpoints, host /
vertex ids in range when bounds are given, loss in [0,1],
latency deltas non-negative (a negative delta would break the
conservative window), crash-before-restart ordering per host; warns
when times do not align to the window length (effects quantize to the
enclosing window boundary).

Inputs: a standalone JSON plan ({"faults": [...]}; see
examples/faultplan_degraded.json) or a shadow.config.xml whose
<fault> elements are checked by name only (name->index resolution
needs a built topology; use --hosts/--vertices for range checks on
raw-integer plans).

Usage: faultplan_lint.py plan.json [--hosts N] [--vertices N]
       [--min-jump-ns NS]
Exit 0 = clean (warnings allowed), 1 = errors.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def lint_text(text: str, *, hosts=None, vertices=None, min_jump_ns=None):
    """Returns (errors, warnings) for a JSON plan or XML config blob."""
    from shadow_tpu.faults.plan import (FaultRecord, KIND_NAMES,
                                        records_from_json,
                                        validate_records, _value_raw)

    stripped = text.lstrip()
    if stripped.startswith("<"):
        from shadow_tpu.config.xmlconfig import parse_config

        cfg = parse_config(text)
        recs = []
        errors = []
        names = {name for name, _ in cfg.expanded_hosts()}
        # Name -> index resolution needs placement; lint with stable
        # symbolic indices so per-host ordering checks (crash before
        # restart) still see distinct endpoints. Range checks are
        # skipped for names (a configured name is in range by
        # construction).
        sym_idx: dict = {}

        def sym(tok):
            return sym_idx.setdefault(str(tok), len(sym_idx))

        for i, spec in enumerate(cfg.faults):
            kname = spec.kind.lower()
            if kname not in KIND_NAMES:
                errors.append(f"<fault> {i} (t={spec.time_ns}): unknown "
                              f"kind '{spec.kind}'")
                continue
            for end in (spec.a, spec.b):
                if end is not None and end not in names:
                    try:
                        int(end)
                    except (TypeError, ValueError):
                        errors.append(
                            f"<fault> {i} (t={spec.time_ns}): '{end}' "
                            f"names no configured host")
            kind = KIND_NAMES[kname]
            recs.append(FaultRecord(
                t_ns=spec.time_ns, kind=kind,
                a=sym(spec.a), b=sym(spec.b) if spec.b is not None else -1,
                value=_value_raw(kind, spec.value)))
        e2, warnings = validate_records(recs, min_jump_ns=min_jump_ns)
        return errors + e2, warnings
    try:
        recs = records_from_json(json.loads(text))
    except (ValueError, KeyError) as e:
        return [str(e)], []
    return validate_records(recs, num_hosts=hosts, num_vertices=vertices,
                            min_jump_ns=min_jump_ns)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate a fault plan offline (JSON plan or "
                    "shadow.config.xml)")
    ap.add_argument("plan", help="plan file (.json) or config (.xml)")
    ap.add_argument("--hosts", type=int, default=None,
                    help="host count for crash/restart range checks")
    ap.add_argument("--vertices", type=int, default=None,
                    help="topology vertex count for link/partition "
                         "range checks")
    ap.add_argument("--min-jump-ns", type=int, default=None,
                    help="window length: warn on times that quantize")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress warnings, print errors only")
    args = ap.parse_args(argv)

    with open(args.plan) as f:
        text = f.read()
    errors, warnings = lint_text(text, hosts=args.hosts,
                                 vertices=args.vertices,
                                 min_jump_ns=args.min_jump_ns)
    for e in errors:
        print(f"ERROR: {e}", file=sys.stderr)
    if not args.quiet:
        for w in warnings:
            print(f"WARNING: {w}", file=sys.stderr)
    if errors:
        print(f"{args.plan}: {len(errors)} error(s), "
              f"{len(warnings)} warning(s)", file=sys.stderr)
        return 1
    print(f"{args.plan}: OK ({len(warnings)} warning(s))",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
