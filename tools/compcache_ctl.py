#!/usr/bin/env python3
"""Operator console for the persistent AOT program store
(shadow_tpu/compile/store.py) — inspect, trim, and pre-populate the
compiled-program cache that warm-start serving loads from.

Subcommands:
  ls                    every entry, oldest-served first (key, size,
                        age, code/jax versions, whether THIS process
                        could serve it)
  stats                 one JSON summary (root, entry count, bytes,
                        code versions present)
  gc --max-bytes N      evict until the store fits in N bytes
                        (suffixes K/M/G ok). Entries from other code
                        versions go first — they can never be served
                        again — then least-recently-served.
  prewarm --config X    build the config's bundle (capacities
                        bucketed, exactly like a fleet scenario) and
                        compile-or-confirm its dispatch program, so
                        the NEXT run of that shape starts dispatching
                        instead of compiling. --exact skips the
                        bucketing; --test uses the built-in example
                        config instead of a file.
  prewarm --sweep X     expand a sweep spec (sweep/plan.py) and
                        prewarm its distinct-program census: one
                        compile per bucket + specialization variant,
                        printed with hit/compile counts — warm a cold
                        pool before `shadow-tpu sweep run` launches.

The store root is $SHADOW_AOT_DIR, else the claimed compile-cache dir
(.jax_cache/<fingerprint-namespace>/aot); --root overrides both.
Exit 0 = ok, 1 = error (gc/prewarm failures; ls/stats of an empty or
missing root are not errors — an empty store is a valid store).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_bytes(s: str) -> int:
    s = s.strip().upper()
    mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}.get(s[-1:], 1)
    return int(float(s[:-1] if mult > 1 else s) * mult)


def _age(mtime: float) -> str:
    d = max(0.0, time.time() - mtime)
    for unit, sec in (("d", 86400), ("h", 3600), ("m", 60)):
        if d >= sec:
            return f"{d / sec:.1f}{unit}"
    return f"{d:.0f}s"


def _store(args):
    from shadow_tpu.compile.store import ProgramStore, default_store

    return ProgramStore(args.root) if args.root else default_store()


def cmd_ls(args) -> int:
    import jax

    from shadow_tpu.compile import buckets

    store = _store(args)
    entries = store.ls()
    code_now, jax_now = buckets.code_version(), jax.__version__
    print(f"# {store.root} — {len(entries)} entries")
    for m in entries:
        servable = (m.get("code") == code_now
                    and m.get("jax") == jax_now)
        # capability vector (compile/specialize.py): a trimmed
        # variant's sidecar records what was dropped — `full` means
        # the general program
        spec = m.get("specialization") or {}
        tag = spec.get("key_extra") or ("full" if not spec.get("dropped")
                                        else "-".join(spec["dropped"]))
        print(f"{m.get('key', '?'):20s} {int(m.get('nbytes', 0)):>12d}B "
              f"{_age(float(m.get('mtime', 0.0))):>7s} "
              f"code={str(m.get('code'))[:8]} jax={m.get('jax')} "
              f"spec={tag} "
              f"{'servable' if servable else 'STALE'}")
    return 0


def cmd_stats(args) -> int:
    print(json.dumps(_store(args).stats(), indent=1, sort_keys=True))
    return 0


def cmd_gc(args) -> int:
    store = _store(args)
    out = store.gc(_parse_bytes(args.max_bytes))
    print(json.dumps(out, indent=1))
    return 0


def cmd_prewarm_sweep(args) -> int:
    """Warm a cold pool for a whole sweep: expand the plan, compute
    its distinct-program census (sweep/plan.py — bucket-affinity keys
    + predicted specialization variants, no build involved), then
    compile-or-confirm ONE representative program per distinct key
    through the same scenario build path the workers take."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from shadow_tpu.apps import phold
    from shadow_tpu.compile import serve
    from shadow_tpu.fleet import scenario
    from shadow_tpu.fleet.affinity import affinity_key
    from shadow_tpu.sweep import plan as plan_mod

    spec = plan_mod.SweepSpec.from_file(args.sweep)
    points = plan_mod.expand(spec)
    specs = [spec.point_spec(p, 0) for p in points]
    census = plan_mod.plan_census(specs)
    print(f"# sweep {spec.id}: {len(specs)} points, "
          f"{census['distinct']} distinct program(s)")
    reps = {}
    for s in specs:
        reps.setdefault(affinity_key(s), s)
    store = _store(args) if args.root else None
    keys, hits = [], 0
    for ak in sorted(reps):
        s = reps[ak]
        caps = {"event_capacity": s.event_capacity,
                "outbox_capacity": s.outbox_capacity,
                "router_ring": s.router_ring}
        b = scenario._build_scenario(s, caps)
        info = serve.prewarm(b, (phold.handler,), store=store,
                             log=lambda m: print(m))
        ok = bool(info.get("hit") or info.get("stored"))
        hits += bool(info.get("hit"))
        keys.append({"affinity_key": ak, "key": info.get("key"),
                     "hit": bool(info.get("hit")), "ok": ok,
                     "count": census["programs"][ak]["count"],
                     "specialization":
                     census["programs"][ak]["specialization"]})
    out = {"sweep": spec.id, "points": len(specs),
           "distinct": census["distinct"], "hits": hits,
           "compiled": len(keys) - hits, "keys": keys}
    print(json.dumps(out, indent=1, sort_keys=True, default=str))
    return 0 if all(k["ok"] for k in keys) else 1


def cmd_prewarm(args) -> int:
    import jax

    if getattr(args, "sweep", None):
        return cmd_prewarm_sweep(args)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from shadow_tpu.compile import serve
    from shadow_tpu.compile.buckets import CAPACITY_KEYS, quantize_pow2
    from shadow_tpu.config.examples import example_config
    from shadow_tpu.config.loader import load
    from shadow_tpu.config.xmlconfig import parse_config

    if args.test:
        text, base = example_config(), None
    elif args.config:
        with open(args.config) as f:
            text = f.read()
        base = os.path.dirname(os.path.abspath(args.config))
    else:
        print("error: prewarm needs --config PATH, --test, or "
              "--sweep SPEC", file=sys.stderr)
        return 1

    loaded = load(parse_config(text), seed=args.seed, base_dir=base)
    b = loaded.bundle
    if not args.exact:
        # quantize AFTER the load so plugin capacity hints are already
        # merged, then rebuild — the same bucket lattice a fleet
        # scenario lands on (fleet/scenario.py), so this prewarms the
        # entry those jobs will actually load
        grown = {k: quantize_pow2(getattr(b.cfg, k))
                 for k in CAPACITY_KEYS
                 if quantize_pow2(getattr(b.cfg, k)) != getattr(b.cfg, k)}
        if grown:
            print(f"bucketing capacities: {grown}")
            b = b.rebuild(grown)
    if args.specialize != "off":
        # prewarm the variant a fleet run of this config will actually
        # serve: the capability-trimmed program when the build proves
        # trims sound, keyed by its own store entry
        # (compile/specialize.py)
        from shadow_tpu.compile import specialize

        b = specialize.apply(b, loaded.handlers,
                             app_bulk=getattr(b, "app_bulk", None),
                             mode=args.specialize)
        if b.caps is not None and b.caps.dropped():
            print(f"specializing: trimmed "
                  f"{','.join(b.caps.dropped())} "
                  f"(key extra {b.caps.key_extra()!r})")
    store = _store(args) if args.root else None
    info = serve.prewarm(b, loaded.handlers, store=store,
                         log=lambda m: print(m))
    print(json.dumps(info, indent=1, sort_keys=True, default=str))
    return 0 if info.get("hit") or info.get("stored") else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="compcache_ctl",
        description="inspect / trim / pre-populate the AOT program store")
    ap.add_argument("--root", help="store root (default: "
                    "$SHADOW_AOT_DIR or the claimed .jax_cache/aot)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("ls", help="list entries, oldest-served first")
    sub.add_parser("stats", help="JSON summary")
    g = sub.add_parser("gc", help="evict down to a byte budget")
    g.add_argument("--max-bytes", required=True,
                   help="target size (suffixes K/M/G ok)")
    p = sub.add_parser("prewarm",
                       help="compile a config's program into the store")
    p.add_argument("--config", help="shadow config XML path")
    p.add_argument("--test", action="store_true",
                   help="use the built-in example config")
    p.add_argument("--sweep",
                   help="sweep spec JSON (sweep/plan.py): prewarm "
                        "the plan's distinct-program census — one "
                        "compile per bucket+specialization variant, "
                        "however many points share it")
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--exact", action="store_true",
                   help="skip capacity bucketing (bespoke shapes)")
    p.add_argument("--specialize", choices=("auto", "off"),
                   default="auto",
                   help="prewarm the capability-trimmed variant the "
                        "fleet will serve (auto, default) or the full "
                        "general program (off)")
    args = ap.parse_args(argv)
    return {"ls": cmd_ls, "stats": cmd_stats, "gc": cmd_gc,
            "prewarm": cmd_prewarm}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
