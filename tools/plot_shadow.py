#!/usr/bin/env python3
"""Plot stats.shadow.json files — the analog of the reference's
src/tools/plot-shadow.py: cross-experiment overlay plots (throughput
time series, per-node CDFs, RAM, retransmits) plus run-time progress
("tick") plots, combined into one multi-page PDF (the reference
combines pages with PdfPages the same way, plot-shadow.py).

Usage: plot_shadow.py -d stats.shadow.json LABEL [-d FILE2 LABEL2 ...]
                      [-o prefix]

Each -d pair adds one experiment; every page overlays all of them —
the comparison workflow the reference's README describes (run two
experiments, parse both, plot both on shared axes).
"""

from __future__ import annotations

import argparse
import json
import sys


def _series(node_block: dict, key: str) -> tuple[list, list]:
    by_sec = node_block.get(key, {})
    xs = sorted(int(k) for k in by_sec)
    ys = [by_sec[str(x)] if str(x) in by_sec else by_sec[x] for x in xs]
    return xs, ys


def _aggregate(stats: dict, key: str) -> dict[int, int]:
    """Per-second totals of `key` over all nodes."""
    acc: dict[int, int] = {}
    for blk in stats["nodes"].values():
        xs, ys = _series(blk, key)
        for x, y in zip(xs, ys):
            acc[x] = acc.get(x, 0) + y
    return acc


def _new_page(plt, title: str):
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.set_title(title, fontsize=11)
    ax.grid(alpha=0.3)
    return fig, ax


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-d", "--data", nargs=2, action="append",
                    metavar=("FILE", "LABEL"), required=True)
    ap.add_argument("-o", "--output-prefix", default="shadow.results")
    args = ap.parse_args(argv)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib.backends.backend_pdf import PdfPages
    except ImportError:
        print("matplotlib unavailable; install it to plot", file=sys.stderr)
        return 1

    experiments = []
    for path, label in args.data:
        with open(path) as f:
            experiments.append((label, json.load(f)))

    pages = [
        ("total recv throughput", "recv_bytes_by_second",
         "MiB/interval", 1 << 20),
        ("total send throughput", "send_bytes_by_second",
         "MiB/interval", 1 << 20),
        ("retransmitted segments", "retransmits_by_second",
         "segments/interval", 1),
        ("buffered RAM (all nodes)", "ram_bytes_by_second",
         "MiB", 1 << 20),
    ]

    out = f"{args.output_prefix}.pdf"
    with PdfPages(out) as pdf:
        # -- aggregate time-series pages, one metric per page ----------
        for title, key, ylabel, scale in pages:
            fig, ax = _new_page(plt, title)
            for label, stats in experiments:
                acc = _aggregate(stats, key)
                xs = sorted(acc)
                if xs:
                    ax.plot(xs, [acc[x] / scale for x in xs], label=label)
            ax.set_xlabel("sim time (s)")
            ax.set_ylabel(ylabel)
            ax.legend(fontsize=8)
            pdf.savefig(fig)
            plt.close(fig)

        # -- per-node total CDF (the cross-experiment fairness view) ---
        fig, ax = _new_page(plt, "per-node total recv (CDF)")
        for label, stats in experiments:
            totals = []
            for blk in stats["nodes"].values():
                _, ys = _series(blk, "recv_bytes_by_second")
                if ys:
                    totals.append(sum(ys))
            if totals:
                totals.sort()
                n = len(totals)
                ax.plot([b / (1 << 20) for b in totals],
                        [(i + 1) / n for i in range(n)], label=label)
        ax.set_xlabel("total recv MiB per node")
        ax.set_ylabel("CDF")
        ax.legend(fontsize=8)
        pdf.savefig(fig)
        plt.close(fig)

        # -- run-time progress ("tick") pages --------------------------
        # periodic [shadow-progress] records: cumulative sim seconds
        # vs wall seconds (the reference's real-time tick plot)
        fig, ax = _new_page(plt, "run-time progress")
        any_prog = False
        for label, stats in experiments:
            pts = [(t["wall_seconds"], t["sim_seconds"])
                   for t in stats.get("ticks", [])
                   if "wall_seconds" in t and "sim_seconds" in t]
            if pts:
                pts.sort()
                ax.plot([p[0] for p in pts], [p[1] for p in pts],
                        label=label, marker=".")
                any_prog = True
        if any_prog:
            ax.set_xlabel("wall time (s)")
            ax.set_ylabel("simulated time (s)")
            ax.legend(fontsize=8)
            pdf.savefig(fig)
        plt.close(fig)

        # whole-run rate comparison bars
        fig, ax = _new_page(plt, "simulated-sec per wall-sec")
        labels, rates = [], []
        for label, stats in experiments:
            sw = next((t["simulated_seconds_per_wall_second"]
                       for t in reversed(stats.get("ticks", []))
                       if t.get("simulated_seconds_per_wall_second")
                       is not None), None)
            if sw is not None:
                labels.append(label)
                rates.append(sw)
        if labels:
            ax.bar(labels, rates, alpha=0.7)
        ax.set_ylabel("simulated-sec / wall-sec")
        pdf.savefig(fig)
        plt.close(fig)

    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
