#!/usr/bin/env python3
"""Plot stats.shadow.json files — the analog of the reference's
src/tools/plot-shadow.py (throughput time series + CDFs across
experiments).

Usage: plot_shadow.py -d stats.shadow.json LABEL [-d ... LABEL2]
                      [-o prefix]
"""

from __future__ import annotations

import argparse
import json
import sys


def _series(node_block: dict, key: str) -> tuple[list, list]:
    by_sec = node_block.get(key, {})
    xs = sorted(int(k) for k in by_sec)
    ys = [by_sec[str(x)] if str(x) in by_sec else by_sec[x] for x in xs]
    return xs, ys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-d", "--data", nargs=2, action="append",
                    metavar=("FILE", "LABEL"), required=True)
    ap.add_argument("-o", "--output-prefix", default="shadow.results")
    args = ap.parse_args(argv)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib unavailable; install it to plot", file=sys.stderr)
        return 1

    fig, axes = plt.subplots(2, 3, figsize=(15, 7))
    (ax_rx, ax_tx, ax_ram), (ax_cdf, ax_retx, ax_prog) = axes

    for path, label in args.data:
        with open(path) as f:
            stats = json.load(f)
        # aggregate per-second totals over all nodes
        rx_tot: dict[int, int] = {}
        tx_tot: dict[int, int] = {}
        retx_tot: dict[int, int] = {}
        final_rx = []
        for node, blk in stats["nodes"].items():
            for key, acc in (("recv_bytes_by_second", rx_tot),
                             ("send_bytes_by_second", tx_tot),
                             ("retransmits_by_second", retx_tot)):
                xs, ys = _series(blk, key)
                for x, y in zip(xs, ys):
                    acc[x] = acc.get(x, 0) + y
            xs, ys = _series(blk, "recv_bytes_by_second")
            if ys:
                final_rx.append(sum(ys))
        for acc, ax, name in ((rx_tot, ax_rx, "recv"), (tx_tot, ax_tx, "send"),
                              (retx_tot, ax_retx, "retransmits")):
            xs = sorted(acc)
            ax.plot(xs, [acc[x] / (1 << 20) for x in xs], label=label)
            ax.set_xlabel("sim time (s)")
            ax.set_ylabel(f"{name} MiB/interval"
                          if name != "retransmits" else "segments/interval")
        if final_rx:
            final_rx.sort()
            n = len(final_rx)
            ax_cdf.plot([b / (1 << 20) for b in final_rx],
                        [(i + 1) / n for i in range(n)], label=label)
            ax_cdf.set_xlabel("total recv MiB per node")
            ax_cdf.set_ylabel("CDF")
        # RAM held in simulated buffers (ref: plot-shadow's RAM panel)
        ram_tot: dict[int, int] = {}
        for node, blk in stats["nodes"].items():
            xs, ys = _series(blk, "ram_bytes_by_second")
            for x, y in zip(xs, ys):
                ram_tot[x] = ram_tot.get(x, 0) + y
        if ram_tot:
            xs = sorted(ram_tot)
            ax_ram.plot(xs, [ram_tot[x] / (1 << 20) for x in xs],
                        label=label)
        ax_ram.set_xlabel("sim time (s)")
        ax_ram.set_ylabel("buffered MiB (all nodes)")
        # run-time progress (ref: plot-shadow's "tick" real-time
        # panel); the LAST tick is the whole-run figure
        sw = next((t["simulated_seconds_per_wall_second"]
                   for t in reversed(stats.get("ticks", []))
                   if t.get("simulated_seconds_per_wall_second")
                   is not None), None)
        if sw is not None:
            ax_prog.bar([label], [sw], alpha=0.7)
        ax_prog.set_ylabel("simulated-sec per wall-sec")

    for ax in axes.flat:
        ax.legend(fontsize=8)
        ax.grid(alpha=0.3)
    fig.tight_layout()
    out = f"{args.output_prefix}.pdf"
    fig.savefig(out)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
