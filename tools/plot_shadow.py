#!/usr/bin/env python3
"""Plot stats.shadow.json files — the analog of the reference's
src/tools/plot-shadow.py: cross-experiment overlay plots (throughput
time series, per-node CDFs, RAM, retransmits) plus run-time progress
("tick") plots, combined into one multi-page PDF (the reference
combines pages with PdfPages the same way, plot-shadow.py).

Usage: plot_shadow.py -d stats.shadow.json LABEL [-d FILE2 LABEL2 ...]
                      [-o prefix]

Each -d pair adds one experiment; every page overlays all of them —
the comparison workflow the reference's README describes (run two
experiments, parse both, plot both on shared axes).
"""

from __future__ import annotations

import argparse
import json
import sys


def _series(node_block: dict, key: str) -> tuple[list, list]:
    by_sec = node_block.get(key, {})
    xs = sorted(int(k) for k in by_sec)
    ys = [by_sec[str(x)] if str(x) in by_sec else by_sec[x] for x in xs]
    return xs, ys


def _aggregate(stats: dict, key: str) -> dict[int, int]:
    """Per-second totals of `key` over all nodes."""
    acc: dict[int, int] = {}
    for blk in stats["nodes"].values():
        xs, ys = _series(blk, key)
        for x, y in zip(xs, ys):
            acc[x] = acc.get(x, 0) + y
    return acc


def _new_page(plt, title: str):
    fig, ax = plt.subplots(figsize=(7, 5))
    ax.set_title(title, fontsize=11)
    ax.grid(alpha=0.3)
    return fig, ax


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-d", "--data", nargs=2, action="append",
                    metavar=("FILE", "LABEL"), required=True)
    ap.add_argument("-o", "--output-prefix", default="shadow.results")
    ap.add_argument("--max-node-lines", type=int, default=100,
                    help="cap per-node lines on 'each node' pages "
                         "(the reference plots every node; huge runs "
                         "drown the page)")
    args = ap.parse_args(argv)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        from matplotlib.backends.backend_pdf import PdfPages
    except ImportError:
        print("matplotlib unavailable; install it to plot", file=sys.stderr)
        return 1

    experiments = []
    for path, label in args.data:
        with open(path) as f:
            experiments.append((label, json.load(f)))

    def has_key(key):
        return any(key in blk for _, stats in experiments
                   for blk in stats["nodes"].values())

    def moving_avg(xs, ys, seconds=60):
        """60 SECOND moving average (the reference's smoothing): the
        window is derived from the tick spacing, so 10 s heartbeat
        intervals average 6 samples, not 60."""
        from collections import deque

        step = min((b - a for a, b in zip(xs, xs[1:]) if b > a),
                   default=1)
        w = max(1, round(seconds / step))
        out_y = []
        acc = 0.0
        win: deque = deque()
        for y in ys:
            win.append(y)
            acc += y
            if len(win) > w:
                acc -= win.popleft()
            out_y.append(acc / len(win))
        return out_y

    def ratio_series(stats, num_key, den_key):
        num = _aggregate(stats, num_key)
        den = _aggregate(stats, den_key)
        xs = sorted(set(num) | set(den))
        ys = [num.get(x, 0) / den[x] if den.get(x) else 0.0 for x in xs]
        return xs, ys

    out = f"{args.output_prefix}.pdf"
    # The reference plotter's shadow page families
    # (src/tools/plot-shadow.py plot_shadow_packets): per direction,
    # {throughput, goodput, fractional goodput, control overhead,
    # fractional control, retrans overhead, fractional retrans} each
    # as {60 s moving average all nodes, 1 s all nodes, 1 s each
    # node}; plus run time, RAM, and per-node CDFs. Pages whose
    # splits are absent from the parse output (v1 logs) are skipped.
    with PdfPages(out) as pdf:
        def ts_pages(metric, key, ylabel, scale, frac_of=None):
            """The reference's three views of one metric."""
            if not (has_key(key) if frac_of is None
                    else has_key(key) and has_key(frac_of)):
                return
            # aggregate ONCE per experiment; both all-nodes views
            # reuse it (the full per-node walk is O(nodes x samples))
            agg = []
            for label, stats in experiments:
                if frac_of is None:
                    acc = _aggregate(stats, key)
                    xs = sorted(acc)
                    ys = [acc[x] / scale for x in xs]
                else:
                    xs, ys = ratio_series(stats, key, frac_of)
                agg.append((label, xs, ys))
            # 60 s moving average, all nodes
            fig, ax = _new_page(
                plt, f"60 second moving average {metric}, all nodes")
            for label, xs, ys in agg:
                if xs:
                    ax.plot(xs, moving_avg(xs, ys), label=label)
            ax.set_xlabel("tick (s)")
            ax.set_ylabel(ylabel)
            ax.legend(fontsize=8)
            pdf.savefig(fig)
            plt.close(fig)
            # 1 second, all nodes
            fig, ax = _new_page(plt, f"1 second {metric}, all nodes")
            for label, xs, ys in agg:
                if xs:
                    ax.plot(xs, ys, label=label)
            ax.set_xlabel("tick (s)")
            ax.set_ylabel(ylabel)
            ax.legend(fontsize=8)
            pdf.savefig(fig)
            plt.close(fig)
            # 1 second, each node (per-node lines, capped)
            fig, ax = _new_page(plt, f"1 second {metric}, each node")
            for label, stats in experiments:
                for i, (name, blk) in enumerate(
                        sorted(stats["nodes"].items())):
                    if i >= args.max_node_lines:
                        break
                    if frac_of is None:
                        xs, ys = _series(blk, key)
                        ys = [y / scale for y in ys]
                    else:
                        nx, ny = _series(blk, key)
                        dx, dy = _series(blk, frac_of)
                        den = dict(zip(dx, dy))
                        xs = nx
                        ys = [y / den[x] if den.get(x) else 0.0
                              for x, y in zip(nx, ny)]
                    if xs:
                        ax.plot(xs, ys, alpha=0.4, linewidth=0.7)
            ax.set_xlabel("tick (s)")
            ax.set_ylabel(ylabel)
            pdf.savefig(fig)
            plt.close(fig)

        for d in ("send", "recv"):
            ts_pages(f"throughput, {d}", f"{d}_bytes_by_second",
                     "MiB/s", 1 << 20)
            ts_pages(f"goodput, {d}", f"{d}_data_bytes_by_second",
                     "MiB/s", 1 << 20)
            ts_pages(f"fractional goodput, {d}",
                     f"{d}_data_bytes_by_second", "fraction", 1,
                     frac_of=f"{d}_bytes_by_second")
            ts_pages(f"control overhead, {d}",
                     f"{d}_control_bytes_by_second", "KiB/s", 1 << 10)
            ts_pages(f"fractional control overhead, {d}",
                     f"{d}_control_bytes_by_second", "fraction", 1,
                     frac_of=f"{d}_bytes_by_second")
        ts_pages("retrans overhead, send",
                 "retransmit_bytes_by_second", "KiB/s", 1 << 10)
        ts_pages("fractional retrans overhead, send",
                 "retransmit_bytes_by_second", "fraction", 1,
                 frac_of="send_bytes_by_second")
        ts_pages("retransmitted segments", "retransmits_by_second",
                 "segments/s", 1)
        ts_pages("buffered RAM", "ram_bytes_by_second", "MiB", 1 << 20)

        # -- per-node total CDFs (cross-experiment fairness views) -----
        for title, key, xlabel in (
                ("per-node total recv (CDF)", "recv_bytes_by_second",
                 "total recv MiB per node"),
                ("per-node total send (CDF)", "send_bytes_by_second",
                 "total send MiB per node"),
                ("per-node goodput share (CDF)",
                 "recv_data_bytes_by_second",
                 "total recv payload MiB per node")):
            if not has_key(key):
                continue
            fig, ax = _new_page(plt, title)
            for label, stats in experiments:
                totals = []
                for blk in stats["nodes"].values():
                    _, ys = _series(blk, key)
                    if ys:
                        totals.append(sum(ys))
                if totals:
                    totals.sort()
                    n = len(totals)
                    ax.plot([b / (1 << 20) for b in totals],
                            [(i + 1) / n for i in range(n)], label=label)
            ax.set_xlabel(xlabel)
            ax.set_ylabel("CDF")
            ax.legend(fontsize=8)
            pdf.savefig(fig)
            plt.close(fig)

        # -- run-time progress ("tick") pages --------------------------
        # periodic [shadow-progress] records: cumulative sim seconds
        # vs wall seconds (the reference's real-time tick plot)
        fig, ax = _new_page(plt, "run-time progress")
        any_prog = False
        for label, stats in experiments:
            pts = [(t["wall_seconds"], t["sim_seconds"])
                   for t in stats.get("ticks", [])
                   if "wall_seconds" in t and "sim_seconds" in t]
            if pts:
                pts.sort()
                ax.plot([p[0] for p in pts], [p[1] for p in pts],
                        label=label, marker=".")
                any_prog = True
        if any_prog:
            ax.set_xlabel("wall time (s)")
            ax.set_ylabel("simulated time (s)")
            ax.legend(fontsize=8)
            pdf.savefig(fig)
        plt.close(fig)

        # whole-run rate comparison bars
        fig, ax = _new_page(plt, "simulated-sec per wall-sec")
        labels, rates = [], []
        for label, stats in experiments:
            sw = next((t["simulated_seconds_per_wall_second"]
                       for t in reversed(stats.get("ticks", []))
                       if t.get("simulated_seconds_per_wall_second")
                       is not None), None)
            if sw is not None:
                labels.append(label)
                rates.append(sw)
        if labels:
            ax.bar(labels, rates, alpha=0.7)
        ax.set_ylabel("simulated-sec / wall-sec")
        pdf.savefig(fig)
        plt.close(fig)

        # -- manifest pages (parse_shadow.py -m run_manifest.json) -----
        # engine-rate views from the telemetry run manifest: windows
        # per wall-second and events per window
        def manifest_bar(title, ylabel, value_fn):
            labels, vals = [], []
            for label, stats in experiments:
                man = stats.get("manifest")
                if not man:
                    continue
                v = value_fn(man)
                if v is not None:
                    labels.append(label)
                    vals.append(v)
            if not labels:
                return
            fig, ax = _new_page(plt, title)
            ax.bar(labels, vals, alpha=0.7)
            ax.set_ylabel(ylabel)
            pdf.savefig(fig)
            plt.close(fig)

        def _windows_per_sec(man):
            w = man.get("counters", {}).get("windows")
            wall = man.get("wall_seconds")
            return w / wall if w and wall else None

        def _events_per_window(man):
            epw = man.get("telemetry", {}).get("events_per_window")
            if epw:
                return epw.get("mean")
            c = man.get("counters", {})
            if c.get("windows"):
                return c.get("events_processed", 0) / c["windows"]
            return None

        manifest_bar("windows per wall-second", "windows/s",
                     _windows_per_sec)
        manifest_bar("events per window (mean)", "events/window",
                     _events_per_window)

        # events-per-window percentile spread across experiments
        fig, ax = _new_page(plt, "events per window (percentiles)")
        any_pct = False
        for label, stats in experiments:
            epw = (stats.get("manifest") or {}).get(
                "telemetry", {}).get("events_per_window")
            if epw:
                ks = [k for k in ("p50", "p90", "p99") if k in epw]
                ax.plot(ks, [epw[k] for k in ks], marker="o",
                        label=label)
                any_pct = True
        if any_pct:
            ax.set_ylabel("events/window")
            ax.legend(fontsize=8)
            pdf.savefig(fig)
        plt.close(fig)

    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
