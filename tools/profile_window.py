"""Time the window-loop components in isolation on the current backend.

Answers "where do the ms/window go" at step_window granularity: each
phase is jitted alone and timed on a representative mid-run PHOLD
snapshot. For op-level attribution use tools/profile_trace.py; for
stage-level bisection inside the bulk pass use tools/profile_bulk2.py.

Usage:  python tools/profile_window.py [--hosts 10240] [--load 8]
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "tpu,cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from tools.perfutil import build_warm_phold, timeit


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=10240)
    ap.add_argument("--load", type=int, default=8)
    ap.add_argument("--sim-s", type=int, default=5)
    args = ap.parse_args()

    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")

    from shadow_tpu.core import engine, events

    H = args.hosts
    w = build_warm_phold(H, args.load, args.sim_s)
    b, sim, wstart = w["bundle"], w["sim"], w["wstart"]
    one_window, step, bulk_fn = w["one_window"], w["step"], w["bulk_fn"]
    cfg = b.cfg
    print(f"H={H} K={cfg.event_capacity} min_jump={b.min_jump}")
    nev = int(jnp.sum(sim.events.fill_count()))
    print(f"mid-run state: {nev} queued events "
          f"({nev / H:.1f}/host), wstart={int(wstart)}")

    wend = wstart + b.min_jump

    t_full = timeit(lambda: one_window(sim, wstart), n=20)
    print(f"\nfull step_window:      {t_full * 1e3:8.2f} ms")

    bulk_j = jax.jit(lambda s: bulk_fn(s, wend))
    t_bulk = timeit(lambda: bulk_j(sim), n=20)
    print(f"bulk_fn only:          {t_bulk * 1e3:8.2f} ms")

    sim_b, _ = jax.block_until_ready(bulk_j(sim))

    fix_j = jax.jit(lambda s: engine.window_fixpoint(
        s, engine.EngineStats.create(), step, wend, cfg.emit_capacity,
        s.net.lane_id))
    t_fix = timeit(lambda: fix_j(sim_b), n=20)
    print(f"fixpoint (post-bulk):  {t_fix * 1e3:8.2f} ms")

    route_j = jax.jit(lambda s: engine._default_route(s))
    sim_f, _ = jax.block_until_ready(fix_j(sim_b))
    t_route = timeit(lambda: route_j(sim_f), n=20)
    print(f"route_outbox:          {t_route * 1e3:8.2f} ms")

    min_j = jax.jit(lambda s: jnp.min(s.events.min_time()))
    t_min = timeit(lambda: min_j(sim), n=20)
    print(f"min_time reduce:       {t_min * 1e3:8.2f} ms")

    def micro(s):
        q, popped = events.pop_earliest(s.events, wend)
        s = s.replace(events=q)
        buf = events.EmitBuffer.create(H, cfg.emit_capacity,
                                       nwords=s.events.words.shape[-1])
        s, buf = step(s, popped, buf)
        q, out = events.apply_emissions(s.events, s.outbox, buf,
                                        s.net.lane_id)
        return s.replace(events=q, outbox=out)

    micro_j = jax.jit(micro)
    t_micro = timeit(lambda: micro_j(sim), n=20)
    print(f"one micro-step:        {t_micro * 1e3:8.2f} ms")

    print(f"\naccounting: bulk {t_bulk*1e3:.1f} + fix {t_fix*1e3:.1f} "
          f"+ route {t_route*1e3:.1f} + min {t_min*1e3:.1f} = "
          f"{(t_bulk+t_fix+t_route+t_min)*1e3:.1f} ms "
          f"vs full {t_full*1e3:.1f} ms")


if __name__ == "__main__":
    main()
