#!/usr/bin/env python3
"""Watch-and-strike daemon for the flaky axon TPU tunnel.

The one v5e chip is reached through a tunnel that wedges for hours and
opens in windows of a few minutes (ROUND3_NOTES.md tunnel log). Facts
this tool is built on, all observed in rounds 2-3:

  - `import jax` is instant; the FIRST jax op triggers backend init,
    and that is what hangs when the tunnel is wedged.
  - A wedged backend init NEVER recovers, even when the tunnel later
    reopens — kill the process and start a fresh one.
  - An ESTABLISHED session survives tunnel flaps that block new inits,
    so the strategy is: hunt with short-timeout init attempts, and the
    moment one lands, HOLD that process and run every queued job in it.
  - The persistent compilation cache (repo-local .jax_cache, shared
    with bench.py/scale_run.py/conftest.py) makes every job after the
    first window cheap: a window spent compiling is banked.

Usage:
    python tools/tpu_watch.py                # hunt + run campaign
    python tools/tpu_watch.py --status       # show probe/result state
    python tools/tpu_watch.py --session      # (internal) one session

The parent loop spawns session subprocesses. A session tries backend
init; if init doesn't complete within --init-timeout the parent kills
it and immediately respawns (no backoff — sleeping loses the race).
When init lands, the session runs the campaign jobs in-process under
the held session, writing one JSON result per job to
.tpu_watch/results/<job>.json; completed jobs are skipped on respawn,
so a session that dies mid-campaign resumes where it left off. The
parent exits when every job has a result. All probe/job activity is
timestamped into .tpu_watch/watch.log (the probe-cadence record).

The campaign (in strike order — the driver-critical cache warm first,
then the cheapest banker, then the r5 headline rows, heaviest last):
  bench_10k          the driver's exact end-of-round shape (10,240-host
                     PHOLD load 8, 5 sim-s) — warms the cache key the
                     driver's bench.py run will hit; nothing matters
                     more than BENCH_r{N} landing on the chip
  bench_1k_quick     smallest real TPU row, lands within ~1 min warm
  relay_ref_1024     BASELINE config #2 PROPER (lossy ref-topology TCP
                     relay, chunked) + a --runahead 50 variant
  tor_10240          shared-relay Tor shape (r5 multiplexed circuits)
  bench_ref_topo     PHOLD on the real 183-vertex reference graph
  relay_10240        BASELINE config #3 (disjoint Tor-relay shape)
  gossip_5120        BASELINE config #4 (Bitcoin gossip)
  bench_1k_x8        ensemble mode: 8 independent 1k replicas
  bench_100k         BASELINE config #5 at spec scale
  tor_102400         the north-star Tor shape at 100k (heaviest
                     compile, so it goes last)

A job that fails the same way twice is terminal (recorded ok=false,
attempts>=2) so one deterministic failure can't pin the campaign in a
respawn loop; the parent exits when every job has a terminal result.
"""

from __future__ import annotations

import argparse
import contextlib
import io
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
STATE = REPO / ".tpu_watch"
RESULTS = STATE / "results"
LOG = STATE / "watch.log"

# one entry per job: (name, kind, spec, per-job alarm seconds), in
# strike order. Jobs run inside the held session via bench.main() /
# scale_run.main() so their device programs (and so their
# compile-cache keys) are IDENTICAL to what the driver and the scale
# harness run. kind 'bench' specs are env for bench.main; kind
# 'scale' specs are scale_run argv.
JOBS = [
    ("bench_10k", "bench", {}, 1800),  # driver defaults: 10240 hosts
    ("bench_1k_quick", "bench",
     {"BENCH_HOSTS": "1024", "BENCH_SIM_SECONDS": "2"}, 900),
    # config #2 PROPER (r5): the lossy reference-topology TCP relay,
    # chunked (the monolithic program exceeds the backend's
    # per-execution limit on this shape — see make_chunked_runner)
    ("relay_ref_1024", "scale",
     ["--workload", "relay", "--hosts", "1024", "--hop", "2",
      "--bytes", "100000", "--sim-seconds", "20", "--topology", "ref",
      "--allow-partial", "--chunk", "32"], 3600),
    # ... and the same with the reference's runahead fidelity trade
    ("relay_ref_1024_ra50", "scale",
     ["--workload", "relay", "--hosts", "1024", "--hop", "2",
      "--bytes", "100000", "--sim-seconds", "20", "--topology", "ref",
      "--allow-partial", "--chunk", "32", "--runahead", "50"], 3600),
    ("bench_ref_topo", "bench",
     {"BENCH_TOPO": "ref", "BENCH_HOSTS": "1024",
      "BENCH_SIM_SECONDS": "2"}, 1800),
    ("relay_10240", "scale",
     ["--workload", "relay", "--hosts", "10240", "--sim-seconds", "30",
      "--allow-partial"], 3600),
    ("gossip_5120", "scale",
     ["--workload", "gossip", "--hosts", "5120", "--sim-seconds", "10"],
     3600),
    # ensemble mode (r4): 8 independent 1k replicas in one program —
    # the small-config row that a lone replica cannot fill lanes for
    ("bench_1k_x8", "bench",
     {"BENCH_HOSTS": "1024", "BENCH_REPLICAS": "8"}, 1800),
    ("bench_100k", "bench",
     {"BENCH_HOSTS": "102400", "BENCH_SIM_SECONDS": "2"}, 3600),
    # CRASH-PRONE TAIL — both of these crashed the TPU worker process
    # on first attempts (the big-TCP-program crash class,
    # ROUND5_NOTES), and a crashed worker poisons every later job in
    # the held session: they go dead last so nothing is lost when
    # they die.
    # TCP gossip (r5, VERDICT #5): the Bitcoin shape over persistent
    # peer connections
    ("gossip_tcp_5120", "scale",
     ["--workload", "gossip", "--gossip-transport", "tcp",
      "--hosts", "5120", "--sim-seconds", "10", "--allow-partial",
      "--chunk", "4"], 3600),
    # shared-relay Tor shape (r5, VERDICT #2)
    ("tor_10240", "scale",
     ["--workload", "tor", "--hosts", "10240", "--bytes", "100000",
      "--sim-seconds", "30", "--allow-partial", "--chunk", "8"], 5400),
]
ALL_JOBS = [j[0] for j in JOBS]
MAX_ATTEMPTS = 2


def log(msg: str) -> None:
    STATE.mkdir(exist_ok=True)
    line = f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} {msg}"
    with open(LOG, "a") as f:
        f.write(line + "\n")
    print(line, flush=True)


def read_result(job: str) -> dict:
    p = RESULTS / f"{job}.json"
    if not p.exists():
        return {}
    try:
        return json.loads(p.read_text())
    except Exception:
        return {}


def finished(job: str) -> bool:
    """Terminal = succeeded, or failed MAX_ATTEMPTS times (so one
    deterministic failure can't pin the campaign in a respawn loop)."""
    r = read_result(job)
    return bool(r.get("ok")) or int(r.get("attempts", 0)) >= MAX_ATTEMPTS


def record(job: str, payload: dict) -> None:
    RESULTS.mkdir(parents=True, exist_ok=True)
    payload.setdefault("attempts",
                       int(read_result(job).get("attempts", 0)) + 1)
    tmp = RESULTS / f"{job}.json.tmp"
    tmp.write_text(json.dumps(payload, indent=1))
    tmp.replace(RESULTS / f"{job}.json")  # atomic: session can die anytime


class JobTimeout(Exception):
    pass


@contextlib.contextmanager
def alarm(seconds: int):
    def fire(signum, frame):
        raise JobTimeout()

    old = signal.signal(signal.SIGALRM, fire)
    signal.alarm(seconds)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def run_session() -> int:
    """One strike: init the backend (caller enforces the timeout by
    killing us), then run every not-yet-done campaign job in-process
    under the held session."""
    sys.path.insert(0, str(REPO))
    os.environ["BENCH_ASSUME_DEVICE"] = "1"   # we ARE the probe
    import jax

    import bench

    bench.enable_compile_cache()
    t0 = time.time()
    devs = jax.devices()
    log(f"session: INIT_OK {len(devs)} device(s) "
        f"[{devs[0].platform}] in {time.time() - t0:.1f}s")
    if devs[0].platform == "cpu":
        log("session: backend is CPU, not striking (tunnel substituted "
            "a CPU client?); exiting")
        return 3

    for name, kind, spec, budget in JOBS:
        if finished(name):
            continue
        log(f"job {name}: start ({kind} {spec})")
        saved_env = dict(os.environ)
        saved_argv = sys.argv
        buf = io.StringIO()
        t0 = time.time()
        try:
            with alarm(budget), contextlib.redirect_stdout(buf):
                if kind == "bench":
                    os.environ.update(spec)
                    bench.main()
                else:
                    sys.path.insert(0, str(REPO / "tools"))
                    import scale_run

                    sys.argv = ["scale_run.py", *spec]
                    scale_run.main()
            line = [ln for ln in buf.getvalue().strip().splitlines()
                    if ln.startswith("{")][-1]
            record(name, {"ok": True, "wall_s": round(time.time() - t0, 1),
                          "result": json.loads(line)})
            log(f"job {name}: OK {line}")
        except JobTimeout:
            record(name, {"ok": False, "error": f"timeout {budget}s"})
            log(f"job {name}: TIMEOUT after {budget}s")
        except SystemExit as e:
            record(name, {"ok": False, "error": f"exit {e.code}",
                          "output_tail": buf.getvalue().strip()[-300:]})
            log(f"job {name}: exited {e.code}; output: "
                f"{buf.getvalue().strip()[-200:]}")
        except Exception as e:  # noqa: BLE001 — keep striking
            record(name, {"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]})
            log(f"job {name}: FAILED {type(e).__name__}: {e}")
        finally:
            os.environ.clear()
            os.environ.update(saved_env)
            sys.argv = saved_argv

    remaining = [j for j in ALL_JOBS if not finished(j)]
    log(f"session: campaign pass complete, {len(remaining)} job(s) "
        f"unfinished: {remaining}")
    return 0 if not remaining else 4


def watch(init_timeout: int, probe_gap: int) -> int:
    """Hunt loop: spawn sessions back-to-back until the campaign is
    complete. INIT_OK is detected via a sentinel line in the session's
    stdout (also logged); a session that doesn't print it within
    init_timeout is killed and immediately replaced."""
    log(f"watch: start (init_timeout={init_timeout}s, "
        f"gap={probe_gap}s, jobs={ALL_JOBS})")
    import queue
    import threading

    attempt = 0
    while True:
        remaining = [j for j in ALL_JOBS if not finished(j)]
        if not remaining:
            log("watch: all campaign jobs terminal; exiting "
                "(TPU released)")
            return 0
        attempt += 1
        proc = subprocess.Popen(
            [sys.executable, __file__, "--session"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        t0 = time.time()
        # a reader THREAD pumps the pipe (select+readline on a
        # buffered text pipe can strand complete lines in the
        # TextIOWrapper buffer, or block on a partial line — either
        # breaks the watchdog); the main thread only ever blocks on
        # the queue with a timeout, so the kill path always works
        lines: queue.Queue = queue.Queue()

        def pump(pipe, q=lines):
            for ln in pipe:
                q.put(ln)
            q.put(None)

        threading.Thread(target=pump, args=(proc.stdout,),
                         daemon=True).start()
        # before INIT_OK the deadline is the init timeout; after, it
        # is the sum of the remaining jobs' alarm budgets + slack —
        # the session's own signal.alarm cannot interrupt a PJRT call
        # blocked in C (a mid-job tunnel flap), so the parent keeps an
        # external kill path at all times
        deadline = t0 + init_timeout
        init_ok = False
        killed = False
        current_job = None
        while True:
            try:
                line = lines.get(timeout=max(
                    0.2, min(5.0, deadline - time.time())))
            except queue.Empty:
                line = ""
            if line is None:   # EOF: session exited
                break
            if time.time() >= deadline:
                # deadline expired — checked on EVERY iteration, not
                # just idle ones (a wedged job can spam warnings
                # forever; output is not progress)
                proc.kill()
                killed = True
                log(f"watch: attempt {attempt} "
                    + ("session watchdog expired mid-campaign; killed"
                       if init_ok else
                       f"no init after {init_timeout}s; killed, "
                       "retrying"))
                # the in-flight job blocked in C past its budget: its
                # in-process alarm never fired, so record the failed
                # attempt here or MAX_ATTEMPTS can never terminate it
                if (init_ok and current_job
                        and not read_result(current_job).get("ok")):
                    record(current_job, {
                        "ok": False,
                        "error": "killed by watch watchdog "
                                 "(session blocked past its budget)"})
                break
            line = line.rstrip()
            if not line:
                continue
            if "INIT_OK" in line:
                init_ok = True
                deadline = (time.time() + 600
                            + sum(j[3] for j in JOBS
                                  if not finished(j[0])))
                log(f"watch: attempt {attempt} STRUCK after "
                    f"{time.time() - t0:.0f}s — session holds the TPU")
            elif " start (" in line and "job " in line:
                current_job = line.split("job ", 1)[1].split(":")[0]
            elif not line.startswith("20"):  # session log()s are
                # already in watch.log; capture everything else
                # (tracebacks, XLA warnings) for post-mortem
                log(f"watch: [session] {line}")
        rc = proc.wait()
        if not killed:
            log(f"watch: session exited rc={rc} after "
                f"{time.time() - t0:.0f}s")
            if rc == 0:
                return 0
            if rc == 3:
                # backend came up as CPU (tunnel substituted a CPU
                # client) — that state won't flip quickly; don't
                # hot-loop full jax inits against it
                log("watch: CPU-backend session; pausing 120s")
                time.sleep(120)
            elif (not init_ok and probe_gap == 0
                    and time.time() - t0 < 5):
                # session died pre-init almost instantly — a
                # deterministic crash, not a wedged tunnel; don't spin
                log("watch: session crashing at startup; pausing 60s")
                time.sleep(60)
        if probe_gap:
            time.sleep(probe_gap)


def status() -> int:
    print(f"log: {LOG}")
    if LOG.exists():
        print("".join(LOG.read_text().splitlines(keepends=True)[-15:]))
    for j in ALL_JOBS:
        p = RESULTS / f"{j}.json"
        print(f"  {j}: {'DONE ' + p.read_text()[:120] if p.exists() else '—'}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--session", action="store_true")
    ap.add_argument("--status", action="store_true")
    ap.add_argument("--init-timeout", type=int, default=150,
                    help="seconds a session may spend in backend init "
                         "before it is killed (a wedged init never "
                         "recovers)")
    ap.add_argument("--probe-gap", type=int, default=0,
                    help="seconds between attempts (default 0: "
                         "back-to-back — sleeping loses the race)")
    args = ap.parse_args()
    if args.status:
        return status()
    if args.session:
        return run_session()
    return watch(args.init_timeout, args.probe_gap)


if __name__ == "__main__":
    raise SystemExit(main())
