"""Times XLA lowering+compilation of the full device program (no run)."""
import time

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from shadow_tpu.apps import bulk
from shadow_tpu.core import simtime
from shadow_tpu.net.build import HostSpec, build
from shadow_tpu.net.state import NetConfig
from shadow_tpu.net.step import make_step_fn
from shadow_tpu.core.engine import run as engine_run

GRAPH = open("tests/test_tcp.py").read().split('GRAPH = """')[1].split('"""')[0]
GRAPH = GRAPH.replace("{LOSS}", "0.0")

cfg = NetConfig(num_hosts=2, end_time=30 * simtime.ONE_SECOND, seed=1)
hosts = [
    HostSpec(name="client", type="client", proc_start_time=simtime.ONE_SECOND),
    HostSpec(name="server", type="server"),
]
b = build(cfg, GRAPH, hosts)
client = jnp.asarray(np.arange(2) == b.host_of("client"))
server = jnp.asarray(np.arange(2) == b.host_of("server"))
b.sim = bulk.setup(b.sim, client_mask=client, server_mask=server,
                   server_ip=b.ip_of("server"), server_port=8080,
                   total_bytes=100_000)

step = make_step_fn(b.cfg, (bulk.handler,))
f = jax.jit(lambda sim: engine_run(
    sim, step, end_time=b.cfg.end_time, min_jump=b.min_jump,
    emit_capacity=b.cfg.emit_capacity, lane_id=sim.net.lane_id))

t0 = time.time()
lowered = f.lower(b.sim)
t1 = time.time()
print(f"lower: {t1-t0:.1f}s")
compiled = lowered.compile()
t2 = time.time()
print(f"compile: {t2-t1:.1f}s")
