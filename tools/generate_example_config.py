#!/usr/bin/env python3
"""Generate example shadow.config.xml + GraphML topology files — the
analog of the reference's src/tools/generate_example_config.py.

Usage:
  generate_example_config.py [-o DIR] [--clients N] [--kib K]
                             [--vertices V] [--latency MS]

Writes DIR/shadow.config.xml and DIR/topology.graphml.xml; the config
references the topology by path, so `python -m shadow_tpu.cli
DIR/shadow.config.xml` runs it directly.
"""

from __future__ import annotations

import argparse
import pathlib
import sys


def topology(vertices: int, latency_ms: float, bw_kibps: int) -> str:
    nodes = "\n".join(
        f'    <node id="v{i}"><data key="up">{bw_kibps}</data>'
        f'<data key="dn">{bw_kibps}</data>'
        f'<data key="ty">{"client" if i else "server"}</data></node>'
        for i in range(vertices))
    edges = []
    for i in range(vertices):
        edges.append(f'    <edge source="v{i}" target="v{i}">'
                     f'<data key="lat">{latency_ms / 2}</data></edge>')
        for j in range(i + 1, vertices):
            edges.append(f'    <edge source="v{i}" target="v{j}">'
                         f'<data key="lat">{latency_ms}</data></edge>')
    return f"""<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="lat" />
  <key attr.name="type" attr.type="string" for="node" id="ty" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="up" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="dn" />
  <graph edgedefault="undirected">
{nodes}
{chr(10).join(edges)}
  </graph>
</graphml>"""


def config(clients: int, kib: int, stoptime: int) -> str:
    # one source of truth for the example body (config/examples.py)
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from shadow_tpu.config.examples import example_body

    body = example_body(clients, kib, server_attrs=' typehint="server"',
                        client_attrs=' typehint="client"')
    return f"""<shadow stoptime="{stoptime}">
  <topology path="topology.graphml.xml"/>
{body}
</shadow>"""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output-dir", default="example")
    ap.add_argument("--clients", type=int, default=10)
    ap.add_argument("--kib", type=int, default=330)
    ap.add_argument("--stoptime", type=int, default=60)
    ap.add_argument("--vertices", type=int, default=2)
    ap.add_argument("--latency", type=float, default=50.0)
    ap.add_argument("--bandwidth", type=int, default=10240,
                    help="client vertex bandwidth (KiB/s)")
    args = ap.parse_args(argv)

    out = pathlib.Path(args.output_dir)
    out.mkdir(parents=True, exist_ok=True)
    (out / "topology.graphml.xml").write_text(
        topology(args.vertices, args.latency, args.bandwidth))
    (out / "shadow.config.xml").write_text(
        config(args.clients, args.kib, args.stoptime))
    print(f"wrote {out}/shadow.config.xml and {out}/topology.graphml.xml")
    print(f"run: python -m shadow_tpu.cli {out}/shadow.config.xml")
    return 0


if __name__ == "__main__":
    sys.exit(main())
