#!/usr/bin/env python3
"""Parse shadow-tpu logs into stats JSON — the analog of the
reference's src/tools/parse-shadow.py (:9-40): stream a (possibly
xz/gz-compressed) log, extract per-interval node throughput from
heartbeat lines and sim-vs-wall progress ticks, emit
stats.shadow.json.

Usage: parse_shadow.py shadow.log [-o stats.shadow.json]
       [-m run_manifest.json]

-m merges the run manifest the CLI writes next to its trace
(telemetry/export.py run_manifest) into the stats under "manifest",
so plot_shadow.py can add the windows/sec and events/window pages
without re-reading the log.
"""

from __future__ import annotations

import argparse
import json
import re
import sys

HEARTBEAT_RE = re.compile(
    r"^(?P<h>\d+):(?P<m>\d+):(?P<s>\d+)\.(?P<ns>\d+) \[\w+\] "
    r"\[(?P<host>[^\]]+)\] \[shadow-heartbeat\] \[node\] "
    r"(?P<fields>[\d,\-]+)")
NODE_FIELDS = ["interval_seconds", "recv_bytes", "send_bytes",
               "recv_data_bytes", "send_data_bytes",
               "recv_control_bytes", "send_control_bytes",
               "send_retransmit_bytes",
               "recv_packets", "send_packets", "retransmitted_segments",
               "dropped_packets"]
# pre-byte-split logs (round-1 format) carried 7 fields
NODE_FIELDS_V1 = ["interval_seconds", "recv_bytes", "send_bytes",
                  "recv_packets", "send_packets",
                  "retransmitted_segments", "dropped_packets"]
RAM_RE = re.compile(
    r"^(?P<h>\d+):(?P<m>\d+):(?P<s>\d+)\.(?P<ns>\d+) \[\w+\] "
    r"\[(?P<host>[^\]]+)\] \[shadow-heartbeat\] \[ram\] (?P<bytes>\d+)")
TICK_RE = re.compile(
    r"^(?P<h>\d+):(?P<m>\d+):(?P<s>\d+)\.(?P<ns>\d+) .*simulation complete "
    r"(?P<json>\{.*\})")
# periodic run-time progress records (cli.py progress_hook — the
# reference's per-round tick heartbeats feeding plot-shadow)
PROGRESS_RE = re.compile(
    r"^(?P<h>\d+):(?P<m>\d+):(?P<s>\d+)\.(?P<ns>\d+) .*"
    r"\[shadow-progress\] (?P<json>\{.*\})")


def _open(path: str):
    if path == "-":
        return sys.stdin
    if path.endswith(".xz"):
        import lzma

        return lzma.open(path, "rt")
    if path.endswith(".gz"):
        import gzip

        return gzip.open(path, "rt")
    return open(path)


def parse(stream):
    nodes: dict[str, dict] = {}
    ticks = []
    for line in stream:
        m = HEARTBEAT_RE.match(line)
        if m:
            t = (int(m["h"]) * 3600 + int(m["m"]) * 60 + int(m["s"]))
            vals = [int(x) for x in m["fields"].split(",")]
            fields = NODE_FIELDS if len(vals) >= len(NODE_FIELDS) \
                else NODE_FIELDS_V1
            rec = dict(zip(fields, vals))
            node = nodes.setdefault(m["host"], {
                "recv_bytes_by_second": {}, "send_bytes_by_second": {},
                "retransmits_by_second": {}, "drops_by_second": {}})
            node["recv_bytes_by_second"][t] = rec["recv_bytes"]
            node["send_bytes_by_second"][t] = rec["send_bytes"]
            node["retransmits_by_second"][t] = rec["retransmitted_segments"]
            node["drops_by_second"][t] = rec["dropped_packets"]
            if "send_retransmit_bytes" in rec:
                node.setdefault("retransmit_bytes_by_second", {})[t] = \
                    rec["send_retransmit_bytes"]
            # the full byte/packet splits drive the reference
            # plotter's goodput / control-overhead / retransmit page
            # families (plot-shadow.py) — store every split present
            for k in ("recv_data_bytes", "send_data_bytes",
                      "recv_control_bytes", "send_control_bytes",
                      "recv_packets", "send_packets"):
                if k in rec:
                    node.setdefault(f"{k}_by_second", {})[t] = rec[k]
            continue
        m = RAM_RE.match(line)
        if m:
            t = (int(m["h"]) * 3600 + int(m["m"]) * 60 + int(m["s"]))
            node = nodes.setdefault(m["host"], {
                "recv_bytes_by_second": {}, "send_bytes_by_second": {},
                "retransmits_by_second": {}, "drops_by_second": {}})
            node.setdefault("ram_bytes_by_second", {})[t] = int(m["bytes"])
            continue
        m = PROGRESS_RE.match(line)
        if m:
            ticks.append(json.loads(m["json"]))
            continue
        m = TICK_RE.match(line)
        if m:
            ticks.append(json.loads(m["json"]))
    return {"nodes": nodes, "ticks": ticks}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("log")
    ap.add_argument("-o", "--output", default="stats.shadow.json")
    ap.add_argument("-m", "--manifest", default=None,
                    help="run_manifest.json to merge (written by the "
                         "CLI's telemetry exporter into "
                         "<data-directory>/)")
    args = ap.parse_args(argv)
    with _open(args.log) as f:
        stats = parse(f)
    extra = ""
    if args.manifest:
        with open(args.manifest) as f:
            stats["manifest"] = json.load(f)
        extra = (f", manifest with "
                 f"{len(stats['manifest'].get('counters', {}))} counters")
    with open(args.output, "w") as f:
        json.dump(stats, f, indent=1)
    print(f"wrote {args.output}: {len(stats['nodes'])} nodes, "
          f"{len(stats['ticks'])} ticks{extra}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
