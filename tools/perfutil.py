"""Shared timing helper for the tools/profile_* scripts.

CAVEAT (learned the hard way on the axon TPU tunnel): re-executing a
jitted program on bit-identical inputs can be served from a device
runtime execution-result cache, measuring nothing (observed: 0.02 ms
for programs whose real device time is >100 ms). timeit() is only
trustworthy when either the inputs change per call, the outputs are
large (cache declines), or the number is cross-checked against a
whole-run measurement. Prefer varying an input scalar per iteration
(see bench.py's distinct-seed pattern) when in doubt.
"""

from __future__ import annotations

import time

import jax


def build_warm_phold(H: int, load: int, sim_s: int = 5, windows: int = 3):
    """Build a PHOLD bundle at bench.py's capacity sizing and advance
    it `windows` windows to a representative mid-run state. Returns
    (bundle, sim, wstart, one_window) where one_window(sim, wstart) ->
    (sim, next_min) is the jitted full window round."""
    import jax.numpy as jnp

    from bench import _build_phold
    from shadow_tpu.apps import phold
    from shadow_tpu.core import engine
    from shadow_tpu.net import bulk as bulkmod
    from shadow_tpu.net.step import make_step_fn

    b = _build_phold(H, load, sim_s)   # includes phold.setup
    step = make_step_fn(b.cfg, (phold.handler,))
    bulk_fn = bulkmod.make_bulk_fn(b.cfg, phold.BULK)

    @jax.jit
    def one_window(sim, wstart):
        wend = wstart + b.min_jump
        sim, stats, next_min = engine.step_window(
            sim, engine.EngineStats.create(), step, wend,
            b.cfg.emit_capacity, sim.net.lane_id, bulk_fn=bulk_fn)
        return sim, next_min

    sim = b.sim
    wstart = jax.block_until_ready(jnp.min(sim.events.min_time()))
    for _ in range(windows):
        sim, wstart = one_window(sim, wstart)
    sim = jax.block_until_ready(sim)
    return {"bundle": b, "sim": sim, "wstart": wstart,
            "one_window": one_window, "step": step, "bulk_fn": bulk_fn}


def timeit(fn, *args, n=10, warm=2):
    """Average wall seconds per call of fn(*args) over n calls after
    warm warmup calls. All n calls dispatch asynchronously and are
    blocked on once, so this measures device throughput, not per-call
    dispatch latency. See module docstring for the result-cache trap."""
    for _ in range(warm):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n
