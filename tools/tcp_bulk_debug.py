#!/usr/bin/env python3
"""Window-by-window commit diagnostics for the TCP bulk pass.

Runs the relay workload through engine.step_window with the pass in
debug mode and prints, per window, how many hosts committed and a
histogram of abort reasons (the `why` bitmask, decoded back to the
_flag call sites in net/tcp_bulk.py by source scan).

Usage:
  python tools/tcp_bulk_debug.py [--hosts 510] [--hop 5]
      [--bytes 100000] [--sim-seconds 20] [--windows-max 40]
      [--topology one|ref]

--topology ref runs on the reference's real 183-vertex Internet graph
(0.5%-per-path loss) — the config #2 regime where aborts are the
steady state; the histogram is the work-list for loss-aware widening.
"""

from __future__ import annotations

import argparse
import pathlib
import re
import sys


def why_legend() -> dict[int, str]:
    """bit value -> one-line description scraped from the _flag call
    sites (bits are assigned in source order)."""
    src = (pathlib.Path(__file__).resolve().parent.parent
           / "shadow_tpu/net/tcp_bulk.py").read_text()
    legend = {}
    for m in re.finditer(
            r"_flag\(\s*bad,\s*why,\s*(.*?),\s*(\d+|1 << \d+)\)", src, re.DOTALL):
        cond = " ".join(m.group(1).split())[:64]
        legend[eval(m.group(2))] = cond  # noqa: S307 — '1 << N' literals
    for bit, name in ((57, "precheck:bootstrap"),
                      (58, "precheck:quiesced"), (59, "precheck:codel"),
                      (60, "precheck:app"), (61, "precheck:no-work")):
        legend[1 << bit] = name
    return legend


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=510)
    ap.add_argument("--hop", type=int, default=5)
    ap.add_argument("--bytes", type=int, default=100_000)
    ap.add_argument("--sim-seconds", type=int, default=20)
    ap.add_argument("--windows-max", type=int, default=10_000)
    ap.add_argument("--seed", type=int, default=2)
    ap.add_argument("--topology", default="one", choices=["one", "ref"])
    ap.add_argument("--device", action="store_true",
                    help="run on the accelerator instead of forcing "
                         "CPU — the per-window host loop makes a "
                         "device-side hang/fault attributable to a "
                         "specific window")
    args = ap.parse_args()

    import jax

    if not args.device:
        jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from shadow_tpu.utils.compcache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    from shadow_tpu.apps import relay
    from shadow_tpu.core import simtime
    from shadow_tpu.core.engine import EngineStats, step_window
    from shadow_tpu.net.build import HostSpec, build
    from shadow_tpu.net.state import NetConfig
    from shadow_tpu.net.step import make_step_fn
    from shadow_tpu.net.tcp_bulk import make_tcp_bulk_fn

    GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
      <key attr.name="latency" attr.type="double" for="edge" id="lat" />
      <key attr.name="bandwidthup" attr.type="int" for="node" id="up" />
      <key attr.name="bandwidthdown" attr.type="int" for="node" id="dn" />
      <graph edgedefault="undirected">
        <node id="v0"><data key="up">102400</data>
        <data key="dn">102400</data></node>
        <edge source="v0" target="v0"><data key="lat">50.0</data></edge>
      </graph>
    </graphml>"""

    if args.topology == "ref":
        import bench

        GRAPH = bench.ref_topology_text()
    H, hop = args.hosts, args.hop
    cfg = NetConfig(num_hosts=H, seed=args.seed,
                    end_time=args.sim_seconds * simtime.ONE_SECOND,
                    sockets_per_host=4, event_capacity=64,
                    outbox_capacity=64, router_ring=64)
    hosts = [HostSpec(name=f"n{i}", proc_start_time=simtime.ONE_SECOND)
             for i in range(H)]
    b = build(cfg, GRAPH, hosts)
    circuits = [list(range(c * hop, (c + 1) * hop))
                for c in range(H // hop)]
    b.sim = relay.setup(b.sim, circuits=circuits, total_bytes=args.bytes)

    step = make_step_fn(cfg, (relay.handler,))
    dbg_bulk = make_tcp_bulk_fn(cfg, relay.TCP_BULK, debug=True)
    legend = why_legend()

    @jax.jit
    def one_window(sim, wstart):
        wend = jnp.minimum(wstart + b.min_jump, cfg.end_time + 1)
        # in-window event-kind census BEFORE the pass (what a
        # precheck:kind abort actually saw)
        inwin = sim.events.time < wend
        kind_census = jnp.zeros((32,), jnp.int32).at[
            jnp.clip(sim.events.kind, 0, 31)].add(inwin.astype(jnp.int32))
        sim, n_bulk, diag = dbg_bulk(sim, wend)
        stats = EngineStats.create()
        sim, stats, next_min = step_window(
            sim, stats, step, wend, emit_capacity=cfg.emit_capacity,
            lane_id=sim.net.lane_id)
        return sim, stats, next_min, n_bulk, diag, kind_census

    sim = b.sim
    wstart = jnp.min(sim.events.min_time())
    total_bulk = total_serial = total_micro = 0
    w = 0
    agg: dict[int, int] = {}
    kind_tot = np.zeros(32, np.int64)
    while w < args.windows_max and int(wstart) <= cfg.end_time:
        sim, stats, next_min, n_bulk, diag, census = one_window(sim, wstart)
        kind_tot += np.asarray(census)
        n_bulk = int(n_bulk)
        micro = int(stats.micro_steps)
        serial_ev = int(stats.events_processed)
        commit = int(np.sum(np.asarray(diag["commit"])))
        why = np.asarray(diag["why"])
        has_work = (why & (1 << 61)) == 0
        aborted = has_work & ~np.asarray(diag["commit"])
        PRECHECK = sum(1 << b for b in range(57, 62))
        GUARD = 1 << 31
        hist = {}
        for h in np.nonzero(aborted)[0][:100000]:
            wv = int(why[h])
            if wv & PRECHECK:
                low = (wv & PRECHECK) & -(wv & PRECHECK)
            else:
                body = wv & ~GUARD
                low = (body & -body) if body else (wv & -wv if wv else 0)
            hist[low] = hist.get(low, 0) + 1
            agg[low] = agg.get(low, 0) + 1
        total_bulk += n_bulk
        total_serial += serial_ev
        total_micro += micro
        top = sorted(hist.items(), key=lambda kv: -kv[1])[:4]
        tops = " ".join(f"{legend.get(k, hex(k))[:40]}x{v}"
                        for k, v in top)
        print(f"w{w:4d} t={int(wstart)/1e9:8.3f}s commit={commit:5d} "
              f"bulk_ev={n_bulk:6d} serial_ev={serial_ev:6d} "
              f"micro={micro:4d} | {tops}", flush=True)
        wstart = next_min
        w += 1
    print(f"\nTOTAL bulk_ev={total_bulk} serial_ev={total_serial} "
          f"micro={total_micro}")
    print("aggregate first-abort reasons:")
    for k, v in sorted(agg.items(), key=lambda kv: -kv[1]):
        print(f"  {v:8d}  {legend.get(k, hex(k))}")
    from shadow_tpu.core.events import EventKind

    names = {getattr(EventKind, n): n for n in dir(EventKind)
             if not n.startswith("_")
             and isinstance(getattr(EventKind, n), int)}
    print("in-window event kinds (pre-pass census):")
    for k in np.nonzero(kind_tot)[0]:
        print(f"  {int(kind_tot[k]):8d}  {names.get(int(k), k)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
