#!/usr/bin/env python3
"""Synthetic injection-trace generator — stress patterns for the
open-system on-ramp (shadow_tpu/inject/, docs/9-injection.md).

Two shapes the declarative <traffic> compiler doesn't express well:

- flash-crowd: many sources converge on one victim host with a rate
  that ramps up to a peak and decays back down (the classic
  thundering-herd curve). Exercises staging backpressure and the
  destination row's event_capacity (drops latch, never silent).
- ddos: a constant-rate saturation flood from every attacker to the
  victim for a fixed duration — the overflow-accounting test vector
  (tiny --event-capacity + this trace => injection.dropped > 0 plus
  the health warning).

Records are tgen-kind events (apps/tgen.py KIND_TGEN, payload
[dst, port, size]) so a config that registers the tgen app turns the
trace into real UDP datagrams; any other scenario still exercises the
full staging/merge/accounting path (unhandled kinds are consumed and
counted, not load-bearing).

Determinism: all jitter comes from random.Random(seed) — same args,
same trace, byte for byte. Events are generated per-source then
merge-sorted, so the t_ns ordering rule holds by construction.

Usage:
  trace_gen.py flash-crowd --hosts 8 --victim 0 --peak-rate 50000 \
      --ramp-s 0.2 --sustain-s 0.1 --out crowd.trace [--binary]
  trace_gen.py ddos --hosts 8 --victim 0 --rate 20000 \
      --duration-s 0.5 --out flood.trace [--binary]

The emitted file round-trips through inject.read_trace and is sized
for --inject-lanes via apps.tgen.lanes_for (printed on stderr).
"""

from __future__ import annotations

import argparse
import heapq
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from shadow_tpu.apps.tgen import KIND_TGEN, lanes_for  # noqa: E402
from shadow_tpu.inject.trace import write_trace        # noqa: E402

ONE_SECOND = 1_000_000_000


def _jittered(period_ns: int, rnd: random.Random) -> int:
    """A send interval around `period_ns` (+-25%), floor 1 ns — keeps
    per-source streams aperiodic so arrivals interleave instead of
    phase-locking into same-timestamp bursts."""
    return max(1, int(period_ns * (0.75 + 0.5 * rnd.random())))


def _source_stream(host: int, victim: int, port: int, size: int,
                   rate_at, rate_max: float, start_ns: int,
                   end_ns: int, rnd: random.Random):
    """Yield (t_ns, record) for one source. Time-varying rates use
    thinning (Lewis-Shedler): walk at the envelope rate `rate_max`,
    keep each slot with probability rate_at(t)/rate_max — the kept
    stream follows the ramp curve with bounded steps (a naive
    1/rate_at(t) walk overshoots the whole ramp where the rate is
    near zero)."""
    t = start_ns
    period = int(ONE_SECOND / rate_max)
    while t < end_ns:
        if rnd.random() * rate_max < rate_at(t):
            yield t, {"t_ns": t, "host": host, "kind": KIND_TGEN,
                      "payload": [victim, port, size]}
        t += _jittered(period, rnd)


def _merge(streams) -> list:
    """Merge per-source streams into one t_ns-sorted trace."""
    # key= keeps timestamp ties from falling through to dict
    # comparison; merge is stable, so ties keep source order
    return [rec for _, rec in heapq.merge(*streams,
                                          key=lambda x: x[0])]


def flash_crowd(*, hosts: int, victim: int, peak_rate: float,
                ramp_s: float, sustain_s: float, start_s: float,
                port: int, size: int, seed: int) -> list:
    """Linear ramp 0 -> peak over ramp_s, hold for sustain_s, linear
    decay back to 0 over ramp_s — per source; the victim sees the sum
    over hosts-1 sources."""
    start = int(start_s * ONE_SECOND)
    ramp = max(1, int(ramp_s * ONE_SECOND))
    sustain = max(0, int(sustain_s * ONE_SECOND))
    end = start + 2 * ramp + sustain

    def rate_at(t: int) -> float:
        dt = t - start
        if dt < ramp:
            return peak_rate * dt / ramp
        if dt < ramp + sustain:
            return peak_rate
        return peak_rate * max(0, end - t) / ramp

    streams = []
    for h in range(hosts):
        if h == victim:
            continue
        # string seeds hash via sha512 (stable across processes);
        # tuple seeds fall back to hash(), which PYTHONHASHSEED
        # randomizes — that would break byte-identical regeneration
        rnd = random.Random(f"{seed}:crowd:{h}")
        streams.append(_source_stream(h, victim, port, size,
                                      rate_at, peak_rate, start, end,
                                      rnd))
    return _merge(streams)


def ddos(*, hosts: int, victim: int, rate: float, duration_s: float,
         start_s: float, port: int, size: int, seed: int) -> list:
    """Constant-rate flood per attacker for duration_s."""
    start = int(start_s * ONE_SECOND)
    end = start + max(1, int(duration_s * ONE_SECOND))
    streams = []
    for h in range(hosts):
        if h == victim:
            continue
        rnd = random.Random(f"{seed}:ddos:{h}")  # see flash_crowd
        streams.append(_source_stream(h, victim, port, size,
                                      lambda t: rate, rate, start,
                                      end, rnd))
    return _merge(streams)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="synthesize injection traces (flash-crowd / ddos)")
    sub = ap.add_subparsers(dest="pattern", required=True)

    def common(p):
        p.add_argument("--hosts", type=int, default=8,
                       help="host count (sources = hosts - 1)")
        p.add_argument("--victim", type=int, default=0,
                       help="destination host index")
        p.add_argument("--start-s", type=float, default=0.1,
                       help="trace start time (simulated seconds)")
        p.add_argument("--port", type=int, default=9100,
                       help="destination UDP port (tgen payload)")
        p.add_argument("--size", type=int, default=64,
                       help="datagram bytes (tgen payload)")
        p.add_argument("--seed", type=int, default=1)
        p.add_argument("--out", required=True, help="trace file path")
        p.add_argument("--binary", action="store_true",
                       help="CRC-framed binary instead of line JSON")

    fc = sub.add_parser("flash-crowd",
                        help="ramp/sustain/decay convergence on one "
                             "victim")
    common(fc)
    fc.add_argument("--peak-rate", type=float, default=10000.0,
                    help="per-source peak events/s")
    fc.add_argument("--ramp-s", type=float, default=0.2,
                    help="ramp-up (and decay) span, simulated s")
    fc.add_argument("--sustain-s", type=float, default=0.1,
                    help="time held at peak, simulated s")

    dd = sub.add_parser("ddos", help="constant-rate saturation flood")
    common(dd)
    dd.add_argument("--rate", type=float, default=10000.0,
                    help="per-attacker events/s")
    dd.add_argument("--duration-s", type=float, default=0.5,
                    help="flood span, simulated s")

    args = ap.parse_args(argv)
    if not 0 <= args.victim < args.hosts:
        ap.error(f"--victim {args.victim} out of range for "
                 f"--hosts {args.hosts}")
    if args.hosts < 2:
        ap.error("need --hosts >= 2 (at least one source)")

    if args.pattern == "flash-crowd":
        events = flash_crowd(
            hosts=args.hosts, victim=args.victim,
            peak_rate=args.peak_rate, ramp_s=args.ramp_s,
            sustain_s=args.sustain_s, start_s=args.start_s,
            port=args.port, size=args.size, seed=args.seed)
    else:
        events = ddos(
            hosts=args.hosts, victim=args.victim, rate=args.rate,
            duration_s=args.duration_s, start_s=args.start_s,
            port=args.port, size=args.size, seed=args.seed)

    n = write_trace(args.out, events, binary=args.binary)
    print(f"{args.pattern}: {n} events -> {args.out} "
          f"(suggest --inject-lanes {lanes_for(n)})", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
