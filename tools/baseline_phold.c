/* baseline_phold.c — CPU stand-in for the reference's PDES hot loop.
 *
 * The reference (Shadow 1.x) cannot be built in this image (no
 * glib-2.0 dev headers, no igraph), so this program re-creates its
 * scheduler hot path with the same semantics and measures events/s on
 * the host CPU, as the published baseline for BASELINE.json:
 *
 *   - per-host locked binary min-heaps of events, ordered by the
 *     4-key comparator (time, dstHost, srcHost, perSourceSeq)
 *     [ref: src/main/core/work/event.c:110-153]
 *   - conservative windowed rounds: threads drain events with
 *     time < windowEnd for their owned hosts, barrier, min-reduce the
 *     next event time, master advances the window by minJump
 *     [ref: scheduler.c:359-414, master.c:450-480]
 *   - host-partitioned worker threads (SP_PARALLEL_HOST_SINGLE)
 *     [ref: scheduler_policy_host_single.c:237-305]
 *   - PHOLD event execution: per-host PRNG draw, random peer,
 *     reliability draw, fixed path latency, push to the destination
 *     host's heap under its lock [ref: worker_sendPacket,
 *     worker.c:243-304; src/test/phold/test_phold.c:36-52]
 *
 * This measures ONLY the scheduler+heap+RNG skeleton — the real
 * reference additionally runs the full UDP socket/NIC/router stack
 * and the interposer boundary per PHOLD message, so this number is an
 * UPPER BOUND on reference throughput (deliberately conservative for
 * our vs_baseline comparison).
 *
 * Build:  gcc -O2 -pthread -o baseline_phold baseline_phold.c
 * Run:    ./baseline_phold [hosts=1024] [load=8] [sim_s=5] [threads=nproc]
 * Output: one JSON line {"events": N, "wall_s": W, "events_per_sec": R}
 */

#define _GNU_SOURCE
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

typedef struct {
    uint64_t time;
    int32_t dst, src;
    uint32_t seq;
} Event;

/* the reference's total deterministic order (event.c:110-153) */
static inline int ev_before(const Event *a, const Event *b) {
    if (a->time != b->time) return a->time < b->time;
    if (a->dst != b->dst) return a->dst < b->dst;
    if (a->src != b->src) return a->src < b->src;
    return a->seq < b->seq;
}

typedef struct {
    Event *heap;
    int count, cap;
    pthread_mutex_t lock;   /* per-host queue lock
                               (scheduler_policy_host_single.c:20-25) */
    uint64_t rng;           /* per-host PRNG stream (random.c) */
    uint32_t seq_ctr;       /* per-source sequence numbers */
} HostQ;

static HostQ *hosts;
static int NH, LOAD, NTHREADS;
static uint64_t SIM_NS, LATENCY_NS, WINDOW_NS;
static pthread_barrier_t round_barrier;
static volatile uint64_t window_start, window_end;
static uint64_t *thread_min_next;   /* per-thread min next-event time */
static uint64_t *thread_events;     /* per-thread executed count */
static volatile int keep_running = 1;

static void hq_push(HostQ *q, Event e) {
    pthread_mutex_lock(&q->lock);
    if (q->count == q->cap) {
        q->cap *= 2;
        q->heap = realloc(q->heap, q->cap * sizeof(Event));
    }
    int i = q->count++;
    while (i > 0) {
        int p = (i - 1) / 2;
        if (ev_before(&e, &q->heap[p])) {
            q->heap[i] = q->heap[p];
            i = p;
        } else break;
    }
    q->heap[i] = e;
    pthread_mutex_unlock(&q->lock);
}

/* pop the head if it falls inside the window, else report its time */
static int hq_pop_window(HostQ *q, uint64_t wend, Event *out,
                         uint64_t *next_time) {
    pthread_mutex_lock(&q->lock);
    if (q->count == 0) {
        *next_time = UINT64_MAX;
        pthread_mutex_unlock(&q->lock);
        return 0;
    }
    if (q->heap[0].time >= wend) {
        *next_time = q->heap[0].time;
        pthread_mutex_unlock(&q->lock);
        return 0;
    }
    *out = q->heap[0];
    Event last = q->heap[--q->count];
    int i = 0;
    for (;;) {
        int l = 2 * i + 1, r = l + 1, m = i;
        Event *h = q->heap;
        if (l < q->count && ev_before(&h[l], &last) &&
            (r >= q->count || ev_before(&h[l], &h[r]))) m = l;
        else if (r < q->count && ev_before(&h[r], &last)) m = r;
        if (m == i) break;
        q->heap[i] = q->heap[m];
        i = m;
    }
    if (q->count) q->heap[i] = last;
    pthread_mutex_unlock(&q->lock);
    return 1;
}

static inline uint64_t xorshift64(uint64_t *s) {
    uint64_t x = *s;
    x ^= x << 13; x ^= x >> 7; x ^= x << 17;
    return *s = x;
}

/* execute one PHOLD event: draw a random peer, apply the reliability
 * Bernoulli (loss 0 on the one-vertex fixture — still drawn, as
 * worker_sendPacket always draws), schedule the next hop */
static inline void phold_execute(int self, Event *e, int tid) {
    HostQ *q = &hosts[self];
    uint64_t r = xorshift64(&q->rng);
    int peer = (int)(r % (uint64_t)NH);
    uint64_t rel_draw = xorshift64(&q->rng);
    (void)rel_draw;
    Event n = { e->time + LATENCY_NS, peer, self, q->seq_ctr++ };
    if (n.time < SIM_NS) hq_push(&hosts[peer], n);
    thread_events[tid]++;
}

typedef struct { int tid, lo, hi; } WorkerArg;

static void *worker(void *argp) {
    WorkerArg *a = (WorkerArg *)argp;
    Event e;
    while (keep_running) {
        uint64_t wend = window_end;
        uint64_t my_min = UINT64_MAX;
        /* host-rotation pop loop
         * (scheduler_policy_host_single.c:237-267) */
        int progress = 1;
        while (progress) {
            progress = 0;
            for (int h = a->lo; h < a->hi; h++) {
                uint64_t nt;
                while (hq_pop_window(&hosts[h], wend, &e, &nt)) {
                    phold_execute(h, &e, a->tid);
                    progress = 1;
                }
            }
        }
        for (int h = a->lo; h < a->hi; h++) {
            pthread_mutex_lock(&hosts[h].lock);
            if (hosts[h].count && hosts[h].heap[0].time < my_min)
                my_min = hosts[h].heap[0].time;
            pthread_mutex_unlock(&hosts[h].lock);
        }
        thread_min_next[a->tid] = my_min;
        /* executeEventsBarrier + collectInfo (scheduler.c:377-408) */
        pthread_barrier_wait(&round_barrier);
        /* master advances the window (master.c:450-480) on tid 0 */
        if (a->tid == 0) {
            uint64_t mn = UINT64_MAX;
            for (int t = 0; t < NTHREADS; t++)
                if (thread_min_next[t] < mn) mn = thread_min_next[t];
            if (mn >= SIM_NS || mn == UINT64_MAX) keep_running = 0;
            else { window_start = mn; window_end = mn + WINDOW_NS; }
        }
        /* prepareRoundBarrier */
        pthread_barrier_wait(&round_barrier);
    }
    return NULL;
}

int main(int argc, char **argv) {
    NH = argc > 1 ? atoi(argv[1]) : 1024;
    LOAD = argc > 2 ? atoi(argv[2]) : 8;
    int sim_s = argc > 3 ? atoi(argv[3]) : 5;
    NTHREADS = argc > 4 ? atoi(argv[4])
                        : (int)sysconf(_SC_NPROCESSORS_ONLN);
    if (NTHREADS > NH) NTHREADS = NH;
    SIM_NS = (uint64_t)sim_s * 1000000000ull;
    LATENCY_NS = 50ull * 1000000ull;   /* one-vertex fixture: 50 ms */
    WINDOW_NS = LATENCY_NS;            /* minJump = min path latency */

    hosts = calloc(NH, sizeof(HostQ));
    for (int h = 0; h < NH; h++) {
        hosts[h].cap = 4 * LOAD + 8;
        hosts[h].heap = malloc(hosts[h].cap * sizeof(Event));
        pthread_mutex_init(&hosts[h].lock, NULL);
        hosts[h].rng = 0x9E3779B97F4A7C15ull ^ (uint64_t)(h + 1);
        /* seed hierarchy analog: distinct per-host streams */
        for (int k = 0; k < 4; k++) xorshift64(&hosts[h].rng);
    }
    /* initial population: `load` self-messages per host in the first
     * window (phold.test.shadow.config.xml:22-26 analog) */
    for (int h = 0; h < NH; h++)
        for (int k = 0; k < LOAD; k++) {
            Event e = { xorshift64(&hosts[h].rng) % LATENCY_NS, h, h,
                        hosts[h].seq_ctr++ };
            hq_push(&hosts[h], e);
        }

    window_start = 0;
    window_end = WINDOW_NS;
    thread_min_next = calloc(NTHREADS, sizeof(uint64_t));
    thread_events = calloc(NTHREADS, sizeof(uint64_t));
    pthread_barrier_init(&round_barrier, NULL, NTHREADS);

    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    pthread_t tids[256];
    WorkerArg args[256];
    int per = (NH + NTHREADS - 1) / NTHREADS;
    for (int t = 0; t < NTHREADS; t++) {
        args[t].tid = t;
        args[t].lo = t * per;
        args[t].hi = (t + 1) * per < NH ? (t + 1) * per : NH;
        pthread_create(&tids[t], NULL, worker, &args[t]);
    }
    for (int t = 0; t < NTHREADS; t++) pthread_join(tids[t], NULL);
    clock_gettime(CLOCK_MONOTONIC, &t1);

    uint64_t total = 0;
    for (int t = 0; t < NTHREADS; t++) total += thread_events[t];
    double wall = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) / 1e9;
    printf("{\"hosts\": %d, \"load\": %d, \"sim_s\": %d, \"threads\": %d, "
           "\"events\": %llu, \"wall_s\": %.4f, \"events_per_sec\": %.1f}\n",
           NH, LOAD, sim_s, NTHREADS,
           (unsigned long long)total, wall, total / wall);
    return 0;
}
