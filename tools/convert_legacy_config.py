#!/usr/bin/env python3
"""Convert old-generation Shadow configs (<node>/<application>,
<software>, <kill time>) into the current <host>/<process> schema —
the analog of the reference's src/tools/convert_multi_app.py config
migration. shadow-tpu's parser accepts BOTH generations directly
(config/xmlconfig.py); this tool exists to normalize files for
editing and diffing.

Usage: convert_legacy_config.py old.xml new.xml
"""

from __future__ import annotations

import sys
import xml.etree.ElementTree as ET


def convert(text: str) -> str:
    root = ET.fromstring(text)
    out = ET.Element("shadow")

    # <kill time="N"/> -> stoptime attribute
    kill = root.find("kill")
    stop = kill.get("time") if kill is not None else root.get("stoptime")
    if stop:
        out.set("stoptime", stop)
    for attr in ("bootstraptime", "preload", "environment"):
        if root.get(attr):
            out.set(attr, root.get(attr))

    topo = root.find("topology")
    if topo is not None:
        out.append(topo)

    # <software>/<plugin> -> <plugin>. The oldest schema's <software>
    # also carries the launch parameters (plugin/time/arguments) that
    # nodes reference by id — keep the elements for process synthesis.
    software: dict = {}
    for el in list(root.iter("software")) + list(root.iter("plugin")):
        software[el.get("id", "")] = el
        p = ET.SubElement(out, "plugin")
        p.set("id", el.get("id", ""))
        p.set("path", el.get("path", el.get("plugin", "")))

    # <node> -> <host>; <application> -> <process>. A node with a
    # `software` reference and no application children gets its
    # process synthesized from the referenced <software> element.
    for node in list(root.iter("node")) + list(root.iter("host")):
        h = ET.SubElement(out, "host")
        for k, v in node.attrib.items():
            if k != "software":
                h.set(k, v)
        apps = list(node.iter("application")) + list(node.iter("process"))
        if not apps and node.get("software") in software:
            apps = [software[node.get("software")]]
        for app in apps:
            pr = ET.SubElement(h, "process")
            pr.set("plugin", app.get("plugin") if app.tag != "software"
                   else app.get("id", ""))
            if app.get("starttime") or app.get("time"):
                pr.set("starttime", app.get("starttime", app.get("time")))
            if app.get("stoptime"):
                pr.set("stoptime", app.get("stoptime"))
            pr.set("arguments", app.get("arguments", ""))

    ET.indent(out)
    return ET.tostring(out, encoding="unicode")


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(f"USAGE: {sys.argv[0]} old.xml new.xml", file=sys.stderr)
        return 1
    with open(argv[0]) as f:
        converted = convert(f.read())
    with open(argv[1], "w") as f:
        f.write(converted + "\n")
    print(f"wrote {argv[1]}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
