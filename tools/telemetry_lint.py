#!/usr/bin/env python3
"""Offline telemetry-output validator — CI gate for the trace /
manifest files the CLI and bench emit, so a malformed export is caught
by the test suite instead of by a blank Perfetto tab.

Checks:

- Trace JSON (--trace): Chrome Trace Event Format schema — top-level
  {"traceEvents": [...]}; every event carries "ph"; "X" (complete)
  events carry numeric ts/dur with dur > 0 and int pid/tid; "C"
  (counter) events carry a name, numeric ts and a non-empty args
  series; "M" (metadata) events carry the known metadata names;
  window events' args hold the per-window counters with sane values
  (events >= 0, qocc_min <= qocc_max); sim-time windows are sorted by
  ts and non-overlapping (warns otherwise — a ring overrun leaves
  gaps, which are legal).
- Manifest JSON (--manifest): required identity keys present
  (config_hash, seed, shards, counters); the telemetry block's
  records_lost is SURFACED — a nonzero loss count without a matching
  health warning in the manifest is an error (silent observability
  loss is exactly what the latch design forbids). The optional
  "dispatch" block (chunked window loop) must be internally coherent:
  windows_per_dispatch >= 1, every per-dispatch window count fits the
  chunk, and the counts sum to counters.windows when both are present.
  The optional "injection" block (open-system traffic) must reconcile
  (injected + dropped + deferred == trace_events), its drops must be
  latched in health, and the per-window injected plane must sum to
  the device latch when no telemetry records were lost.
  The optional "lanes" block (lane-isolated packed runs) must carry
  one per_lane entry per replica whose overflow shares sum to the
  run-total latch counters exactly, and every quarantined lane must
  name its trips and (when the supervisor's lane surgery ran) its
  salvage pointer + requeue context.
  The optional "causality" block (causal critical-path profiling)
  must conserve its sampling accounting (harvested + lost_ring <=
  sampled <= emitted), its binding-cause counts must cover the
  attributed windows exactly, its chains must be time-contiguous with
  same-host depth strictly increasing, and its traffic matrix must
  agree with the flow recorder's on a lossless equal-period run.

- Fleet manifest JSON (--fleet-manifest): shadow_tpu/fleet schema —
  attempt histories monotone non-decreasing with attempts at the
  high-water mark, every terminal job carries the matching verdict,
  every quarantined job carries its salvage pointers, the counts
  block agrees with the per-job statuses, and packed jobs' lane
  requeues are replicas=1 children back-linked via lane_of.

Usage: telemetry_lint.py [--trace trace.json]
                         [--manifest run_manifest.json]
                         [--fleet-manifest fleet_manifest.json]
Exit 0 = clean (warnings allowed), 1 = errors.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# metadata record names Chrome/Perfetto understand (trace event format
# spec §Metadata Events) — anything else is silently ignored by the
# viewers, which usually means a typo here
KNOWN_METADATA = {
    "process_name", "process_labels", "process_sort_index",
    "thread_name", "thread_sort_index",
}
WINDOW_ARGS = ("events", "micro_steps", "routed_local", "routed_cross",
               "drops", "retx", "active_lanes", "fastpath")

# canonical id formats (shadow_tpu/compile/buckets.py program_key,
# shadow_tpu/fleet/affinity.py affinity_key) — validated by regex so
# the lint stays importable without the engine's jax dependency
_PROGRAM_KEY = re.compile(r"^pk[0-9a-f]{16}$")
_AFFINITY_KEY = re.compile(r"^ak[0-9a-f]{16}$")

# the resident-admission degradation ladder, in order
# (fleet/admission.py LADDER) — duplicated literally so the lint stays
# importable without the engine
_LEASE_LADDER = ("nominal", "stride", "defer", "evict", "quarantine")


def _lint_compile_block(comp, where: str) -> tuple[list, list]:
    """(errors, warnings) for one program-store accounting block
    (compile/serve.py WarmFn info; nested once under "warmup" for the
    bench's fresh-vs-cached pairing)."""
    errors: list = []
    warnings: list = []
    if not isinstance(comp, dict):
        return ([f"{where} must be an object"], [])
    key = comp.get("key")
    if key is not None and (not isinstance(key, str)
                            or not _PROGRAM_KEY.match(key)):
        errors.append(f'{where}.key must match "pk" + 16 hex chars '
                      f"(compile/buckets.py program_key), got {key!r}")
    for k in ("warm", "hit", "stored"):
        v = comp.get(k)
        if v is not None and not isinstance(v, bool):
            errors.append(f"{where}.{k} must be a bool, got {v!r}")
    for k in ("load_s", "lower_s", "compile_s", "warm_speedup"):
        v = comp.get(k)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            errors.append(f"{where}.{k} must be a non-negative "
                          f"number, got {v!r}")
    fb = comp.get("fallback")
    if fb is not None and (not isinstance(fb, str) or not fb):
        errors.append(f"{where}.fallback must be a non-empty string")
    # hit/miss consistency: a hit is a store load (load_s, no compile
    # timings); a clean miss compiled fresh (lower_s/compile_s, no
    # load_s); a fallback may carry neither
    hit = comp.get("hit")
    if hit is True:
        if comp.get("load_s") is None:
            errors.append(f"{where}: hit=true must record load_s "
                          f"(the warm load IS the claimed saving)")
        for k in ("lower_s", "compile_s"):
            if comp.get(k) is not None:
                errors.append(f"{where}: hit=true cannot also carry "
                              f"{k} — a warm serve never compiled")
    elif hit is False and comp.get("warm") and fb is None:
        if comp.get("compile_s") is None:
            errors.append(f"{where}: a warm-serving miss must record "
                          f"its fresh compile_s")
        if comp.get("load_s") is not None:
            errors.append(f"{where}: hit=false cannot carry load_s")
    if hit is True and key is None:
        errors.append(f"{where}: hit=true without a program key")
    # bucket plan: every quantized knob's bucket must be a power of
    # two (or 0 = knob off) and must never shrink the request
    bk = comp.get("buckets")
    if bk is not None:
        if not isinstance(bk, dict):
            errors.append(f"{where}.buckets must be an object")
            bk = {}
        for knob, ent in sorted(bk.items()):
            w2 = f"{where}.buckets.{knob}"
            if not isinstance(ent, dict):
                errors.append(f"{w2} must be an object with "
                              f"requested/bucketed")
                continue
            req, got = ent.get("requested"), ent.get("bucketed")
            for k, v in (("requested", req), ("bucketed", got)):
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errors.append(f"{w2}.{k} must be a non-negative "
                                  f"integer, got {v!r}")
            if isinstance(req, int) and isinstance(got, int) \
                    and not isinstance(req, bool) \
                    and not isinstance(got, bool):
                if got < req:
                    errors.append(f"{w2}: bucketed={got} < requested="
                                  f"{req} — quantization only pads, "
                                  f"never shrinks")
                if got and got & (got - 1):
                    errors.append(f"{w2}: bucketed={got} is not a "
                                  f"power of two")
    return errors, warnings


_SPEC_TRIMMABLE = ("loss", "timers")


def _lint_specialization(spec, ctr, health) -> tuple[list, list]:
    """(errors, warnings) for a manifest's "specialization" block
    (compile/specialize.py specialization_block). The invariants are
    the safety contract of capability trimming: the dropped list must
    be the trimmable subset of the capability vector's False flags,
    the program-key extra must be derived from exactly that list, a
    dropped loss capability means the reliability drop counter was
    structurally never written (so it is exactly zero), and a tripped
    guard latch is a FATAL health verdict — never a silent integer."""
    errors: list = []
    warnings: list = []
    w = "specialization"
    if not isinstance(spec, dict):
        return ([f"{w} must be an object"], [])
    mode = spec.get("mode")
    if mode != "auto":
        errors.append(f'{w}.mode must be "auto" (a --specialize off '
                      f"run writes no block), got {mode!r}")
    caps = spec.get("capabilities")
    if not isinstance(caps, dict):
        errors.append(f"{w}.capabilities must be an object")
        caps = {}
    for k, v in sorted(caps.items()):
        if not isinstance(v, bool):
            errors.append(f"{w}.capabilities.{k} must be a bool, "
                          f"got {v!r}")
    dropped = spec.get("dropped")
    if not isinstance(dropped, list):
        errors.append(f"{w}.dropped must be a list")
        dropped = []
    for n in dropped:
        if n not in _SPEC_TRIMMABLE:
            errors.append(f"{w}.dropped contains {n!r} — only "
                          f"{list(_SPEC_TRIMMABLE)} are trimmable")
        elif caps.get(n) is not False:
            errors.append(
                f"{w}: {n!r} is dropped but capabilities.{n} is "
                f"{caps.get(n)!r} — a dropped capability must be "
                f"recorded dead in the vector")
    for n in _SPEC_TRIMMABLE:
        if caps.get(n) is False and n not in dropped:
            errors.append(
                f"{w}: capabilities.{n}=false but {n!r} is not in "
                f"dropped — a dead trimmable capability is always "
                f"trimmed")
    want_extra = "-".join(
        "no_" + n for n in sorted(x for x in dropped
                                  if x in _SPEC_TRIMMABLE)) or None
    if spec.get("key_extra") != want_extra:
        errors.append(
            f"{w}.key_extra={spec.get('key_extra')!r} does not match "
            f"the dropped list (expected {want_extra!r}) — the store "
            f"key and the manifest must name the same variant")
    # guard latch: one watch per dropped capability, counters are
    # non-negative, and a nonzero counter MUST coincide with a fatal
    # health verdict (the whole point of the latch)
    g = spec.get("guard")
    tripped = 0
    if g is not None:
        if not isinstance(g, dict):
            errors.append(f"{w}.guard must be an object")
            g = {}
        watched = g.get("watched")
        if isinstance(watched, list) and sorted(watched) != \
                sorted(x for x in dropped if x in _SPEC_TRIMMABLE):
            errors.append(
                f"{w}.guard.watched={watched} must equal the dropped "
                f"list {sorted(dropped)} — every trimmed capability "
                f"is watched, nothing else is")
        for k in ("loss_trips", "timer_trips"):
            v = g.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{w}.guard.{k} must be a non-negative "
                              f"integer, got {v!r}")
            else:
                tripped += v
        if tripped:
            hg = (health or {}).get("guard", {}) \
                if isinstance(health, dict) else {}
            surfaced = bool(hg.get("tripped")) or any(
                "specialization guard tripped" in d
                for d in (health or {}).get("diagnostics", [])
                if isinstance(d, str))
            if not surfaced:
                errors.append(
                    f"{w}.guard counters are nonzero "
                    f"(loss={g.get('loss_trips')}, "
                    f"timer={g.get('timer_trips')}) but the health "
                    f"block does not report the trip as fatal — a "
                    f"violated trim assumption must fail the run, "
                    f"never degrade it silently")
            else:
                warnings.append(
                    f"{w}: guard latch tripped {tripped} window(s) — "
                    f"the run was (correctly) reported fatal; rerun "
                    f"with --specialize off")
    elif dropped:
        warnings.append(
            f"{w}: dropped={dropped} but no guard block — the final "
            f"sim was not available to the manifest writer")
    if "loss" in dropped and not tripped:
        dr = (ctr or {}).get("drops_reliability_total")
        if dr is not None and dr != 0:
            errors.append(
                f"counters.drops_reliability_total={dr} but the loss "
                f"capability was trimmed — the trimmed program cannot "
                f"write that counter; the manifest is lying about "
                f"which program ran")
    return errors, warnings


_FLOW_HIST_KEY = re.compile(r"^lane\d+/\d+->\d+/k-?\d+$")


def _lint_flows(fl, ctr, tel) -> tuple[list, list]:
    """(errors, warnings) for a manifest's "flows" block
    (telemetry/flows.py flows_manifest_block). The invariants are the
    flow ring's accounting identities: the device splits every sampled
    packet into appended-or-clamped (recorded + lost_window_clamp ==
    sampled), the harvester splits every recorded slot into
    pulled-or-overrun (harvested + lost_ring <= recorded; < only
    after a checkpoint rewind discarded replayed records), and every
    harvested record lands in exactly one histogram key, one lane,
    and one traffic-matrix cell."""
    errors: list = []
    warnings: list = []
    if not isinstance(fl, dict):
        return (["flows must be an object"], [])
    for k in ("sample_period", "path_shards"):
        v = fl.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(f"flows.{k} must be an integer >= 1, "
                          f"got {v!r}")
    counts = {}
    for k in ("sampled", "recorded", "harvested", "lost_ring",
              "lost_window_clamp"):
        v = fl.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"flows.{k} must be a non-negative integer, "
                          f"got {v!r}")
        else:
            counts[k] = v
    if len(counts) == 5:
        if counts["recorded"] + counts["lost_window_clamp"] \
                != counts["sampled"]:
            errors.append(
                f"flows accounting broken: recorded="
                f"{counts['recorded']} + lost_window_clamp="
                f"{counts['lost_window_clamp']} != sampled="
                f"{counts['sampled']} — the device splits every "
                f"sampled packet into appended or clamped, never "
                f"drops one silently")
        if counts["harvested"] + counts["lost_ring"] \
                > counts["recorded"]:
            errors.append(
                f"flows: harvested={counts['harvested']} + lost_ring="
                f"{counts['lost_ring']} exceeds recorded="
                f"{counts['recorded']}")
        if counts["lost_ring"]:
            warnings.append(
                f"{counts['lost_ring']} flow record(s) lost to ring "
                f"overrun (raise --flow-capacity or drain more often)")
        if counts["lost_window_clamp"]:
            warnings.append(
                f"{counts['lost_window_clamp']} sampled flow(s) "
                f"clamped on device (one window sampled more than the "
                f"ring holds; raise --flow-capacity or the sample "
                f"period)")
    ev = (ctr or {}).get("events_processed")
    if isinstance(ev, int) and not isinstance(ev, bool) \
            and isinstance(fl.get("sampled"), int) \
            and fl.get("sample_period") == 1 and fl["sampled"] > ev:
        # at 1-in-1 sampling every cross-host send is sampled, and a
        # send needs an executed event behind it; coarser periods make
        # the bound vacuous, so only the exhaustive case is checked
        errors.append(
            f"flows.sampled={fl['sampled']} exceeds "
            f"counters.events_processed={ev} at sample_period=1 — "
            f"more packets sampled than events executed")
    if isinstance(tel, dict) and tel.get("flows_sampled") is not None:
        for mk, fk in (("flows_sampled", "sampled"),
                       ("flows_harvested", "harvested"),
                       ("flows_lost_ring", "lost_ring"),
                       ("flows_lost_window_clamp", "lost_window_clamp")):
            if (isinstance(tel.get(mk), int)
                    and isinstance(fl.get(fk), int)
                    and tel[mk] != fl[fk]):
                errors.append(
                    f"telemetry.{mk}={tel[mk]} disagrees with "
                    f"flows.{fk}={fl[fk]} — one harvester fills both "
                    f"blocks, they cannot diverge")
    harvested = fl.get("harvested")
    hist = fl.get("histograms")
    hist_total = 0
    if hist is not None:
        if not isinstance(hist, dict):
            errors.append("flows.histograms must be an object")
            hist = {}
        for key in sorted(hist):
            where = f"flows.histograms[{key}]"
            if not _FLOW_HIST_KEY.match(key):
                errors.append(
                    f'{where}: key must look like '
                    f'"lane<r>/<src_shard>-><dst_shard>/k<kind>"')
            h = hist[key]
            if not isinstance(h, dict):
                errors.append(f"{where}: must be an object")
                continue
            c = h.get("count")
            if not isinstance(c, int) or isinstance(c, bool) or c < 1:
                errors.append(f"{where}: count must be an integer "
                              f">= 1 (empty keys are omitted)")
                c = 0
            hist_total += c
            pcts = [h.get(k) for k in ("p50_ns", "p95_ns", "p99_ns")]
            for k, v in zip(("p50_ns", "p95_ns", "p99_ns"), pcts):
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errors.append(f"{where}: {k} must be a "
                                  f"non-negative integer, got {v!r}")
            if all(isinstance(v, int) and not isinstance(v, bool)
                   for v in pcts) and not (pcts[0] <= pcts[1]
                                           <= pcts[2]):
                errors.append(f"{where}: percentiles must be "
                              f"monotone (p50 <= p95 <= p99), "
                              f"got {pcts}")
            bk = h.get("buckets")
            if not isinstance(bk, dict) or not bk:
                errors.append(f"{where}: buckets must be a non-empty "
                              f"object")
                continue
            los, bsum, ok = [], 0, True
            for lo, n in bk.items():
                try:
                    lov = int(lo)
                except (TypeError, ValueError):
                    errors.append(f"{where}: bucket key {lo!r} is not "
                                  f"an integer lower bound")
                    ok = False
                    continue
                if lov != 0 and (lov < 0 or lov & (lov - 1)):
                    errors.append(f"{where}: bucket lower bound {lov} "
                                  f"is neither 0 nor a power of two "
                                  f"(log2 latency buckets)")
                if (not isinstance(n, int) or isinstance(n, bool)
                        or n < 1):
                    errors.append(f"{where}: bucket[{lo}] count must "
                                  f"be an integer >= 1")
                    ok = False
                else:
                    los.append(lov)
                    bsum += n
            if los != sorted(los):
                errors.append(f"{where}: bucket bounds must be "
                              f"ascending, got {los}")
            if ok and isinstance(c, int) and c and bsum != c:
                errors.append(f"{where}: buckets sum to {bsum} but "
                              f"count={c}")
        if isinstance(harvested, int) and hist \
                and hist_total != harvested:
            errors.append(
                f"flows.histograms cover {hist_total} record(s) but "
                f"harvested={harvested} — every harvested record "
                f"lands in exactly one (lane, path, kind) key")
    per_lane = fl.get("per_lane")
    if per_lane is not None:
        if not isinstance(per_lane, dict):
            errors.append("flows.per_lane must be an object")
            per_lane = {}
        lane_total = 0
        for lane in sorted(per_lane):
            where = f"flows.per_lane[{lane}]"
            try:
                int(lane)
            except (TypeError, ValueError):
                errors.append(f"{where}: lane key must be an integer")
            d = per_lane[lane]
            if not isinstance(d, dict) or not isinstance(
                    d.get("count"), int):
                errors.append(f"{where}: must carry an integer count")
                continue
            lane_total += d["count"]
        if isinstance(harvested, int) and per_lane \
                and lane_total != harvested:
            errors.append(
                f"flows.per_lane counts sum to {lane_total} but "
                f"harvested={harvested} — every record has exactly "
                f"one lane")
    tm = fl.get("traffic_matrix")
    if tm is not None:
        S = fl.get("path_shards")
        if not isinstance(tm, list) or (
                isinstance(S, int) and len(tm) != S) or not all(
                isinstance(row, list)
                and (not isinstance(S, int) or len(row) == S)
                and all(isinstance(c, int) and not isinstance(c, bool)
                        and c >= 0 for c in row)
                for row in tm):
            errors.append(f"flows.traffic_matrix must be a "
                          f"path_shards x path_shards grid of "
                          f"non-negative integers")
        elif isinstance(harvested, int) and sum(
                c for row in tm for c in row) != harvested:
            errors.append(
                f"flows.traffic_matrix sums to "
                f"{sum(c for row in tm for c in row)} but harvested="
                f"{harvested} — every record crosses exactly one "
                f"(src_shard, dst_shard) cell")
    return errors, warnings


# binding-cause names (telemetry/causality.py CAUSE_NAMES) —
# duplicated literally so the lint stays importable without jax
_CAUSE_NAMES = {"min_jump_floor", "adaptive_edge", "fault_record",
                "inject_horizon", "end_time"}
_BINDING_EDGE_KEY = re.compile(r"^v\d+->v\d+$")


def _lint_causality(cz, tel, flows) -> tuple[list, list]:
    """(errors, warnings) for a manifest's "causality" block
    (telemetry/causality.py causality_manifest_block). The invariants:
    every sampled emission was appended to its per-host sub-ring, so
    the harvester splits sampled into pulled-or-overrun (harvested +
    lost_ring <= sampled; < only after a checkpoint rewind discarded
    replayed records); the device kept at most what it saw (sampled <=
    emitted); every attributed window carries exactly one binding
    cause (cause counts sum to windows_attributed); chains are
    time-ordered with same-host depth strictly increasing; and the
    lineage traffic matrix agrees with the flow recorder's when both
    ran lossless at the same sampling period."""
    errors: list = []
    warnings: list = []
    if not isinstance(cz, dict):
        return (["causality must be an object"], [])
    for k in ("sample_period", "path_shards"):
        v = cz.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(f"causality.{k} must be an integer >= 1, "
                          f"got {v!r}")
    counts = {}
    for k in ("sampled", "emitted", "harvested", "lost_ring",
              "cross_host_harvested", "windows_attributed",
              "windows_lost"):
        v = cz.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"causality.{k} must be a non-negative "
                          f"integer, got {v!r}")
        else:
            counts[k] = v
    if len(counts) == 7:
        if counts["sampled"] > counts["emitted"]:
            errors.append(
                f"causality: sampled={counts['sampled']} exceeds "
                f"emitted={counts['emitted']} — the recorder cannot "
                f"keep more emissions than it observed")
        if counts["harvested"] + counts["lost_ring"] \
                > counts["sampled"]:
            errors.append(
                f"causality: harvested={counts['harvested']} + "
                f"lost_ring={counts['lost_ring']} exceeds sampled="
                f"{counts['sampled']} — every sampled emission is "
                f"appended exactly once")
        if counts["cross_host_harvested"] > counts["harvested"]:
            errors.append(
                f"causality: cross_host_harvested="
                f"{counts['cross_host_harvested']} exceeds harvested="
                f"{counts['harvested']}")
        if counts["lost_ring"]:
            warnings.append(
                f"{counts['lost_ring']} lineage record(s) lost to "
                f"ring overrun (raise --causality-capacity or the "
                f"sample period) — chains may be truncated")
        if counts["windows_lost"]:
            warnings.append(
                f"{counts['windows_lost']} window attribution(s) "
                f"lost to advance-ring overrun")
    if isinstance(tel, dict) \
            and tel.get("causality_sampled") is not None:
        for mk, ck in (("causality_sampled", "sampled"),
                       ("causality_harvested", "harvested"),
                       ("causality_lost_ring", "lost_ring"),
                       ("causality_windows_attributed",
                        "windows_attributed")):
            if (isinstance(tel.get(mk), int)
                    and isinstance(cz.get(ck), int)
                    and tel[mk] != cz[ck]):
                errors.append(
                    f"telemetry.{mk}={tel[mk]} disagrees with "
                    f"causality.{ck}={cz[ck]} — one harvester fills "
                    f"both blocks, they cannot diverge")
    # binding-cause histogram: every attributed window has exactly one
    # cause, so the counts must cover windows_attributed exactly
    causes = cz.get("causes")
    cause_total = 0
    if causes is not None:
        if not isinstance(causes, dict):
            errors.append("causality.causes must be an object")
            causes = {}
        for name, n in sorted(causes.items()):
            if name not in _CAUSE_NAMES:
                errors.append(f"causality.causes[{name!r}]: unknown "
                              f"binding cause (expected one of "
                              f"{sorted(_CAUSE_NAMES)})")
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                errors.append(f"causality.causes[{name!r}] must be an "
                              f"integer >= 1 (empty causes are "
                              f"omitted)")
            else:
                cause_total += n
        if isinstance(cz.get("windows_attributed"), int) \
                and cause_total != cz["windows_attributed"]:
            errors.append(
                f"causality.causes cover {cause_total} window(s) but "
                f"windows_attributed={cz['windows_attributed']} — "
                f"every attributed window has exactly one binding "
                f"cause")
    edges = cz.get("edges")
    edge_total = 0
    if edges is not None:
        if not isinstance(edges, dict):
            errors.append("causality.edges must be an object")
            edges = {}
        for key, n in sorted(edges.items()):
            if not _BINDING_EDGE_KEY.match(key):
                errors.append(f'causality.edges key {key!r} must look '
                              f'like "v<a>->v<b>"')
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                errors.append(f"causality.edges[{key!r}] must be an "
                              f"integer >= 1")
            else:
                edge_total += n
        adaptive = (causes or {}).get("adaptive_edge", 0)
        if isinstance(adaptive, int) and edge_total > adaptive:
            errors.append(
                f"causality.edges cover {edge_total} window(s) but "
                f"only {adaptive} window(s) were adaptive-edge bound "
                f"— a binding edge exists only where the live table "
                f"was the constraint")
    # per-window advance records: one per attributed window, each
    # jump within its unclamped lookahead
    advances = cz.get("advances")
    if advances is not None:
        if not isinstance(advances, list):
            errors.append("causality.advances must be an array")
            advances = []
        if isinstance(cz.get("windows_attributed"), int) \
                and len(advances) != cz["windows_attributed"]:
            errors.append(
                f"causality.advances holds {len(advances)} record(s) "
                f"but windows_attributed={cz['windows_attributed']}")
        for i, a in enumerate(advances):
            where = f"causality.advances[{i}]"
            if not isinstance(a, dict):
                errors.append(f"{where}: must be an object")
                continue
            if a.get("cause") not in _CAUSE_NAMES:
                errors.append(f"{where}: unknown cause "
                              f"{a.get('cause')!r}")
            for k in ("jump", "raw"):
                v = a.get(k)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    errors.append(f"{where}: {k} must be a "
                                  f"non-negative integer, got {v!r}")
            if isinstance(a.get("jump"), int) \
                    and isinstance(a.get("raw"), int) \
                    and a["raw"] > 0 and a["jump"] > a["raw"]:
                errors.append(
                    f"{where}: jump={a['jump']} exceeds the unclamped "
                    f"lookahead raw={a['raw']} — clamps only shrink "
                    f"windows")
            u = a.get("utilization_pct")
            if u is not None and (not isinstance(u, int)
                                  or isinstance(u, bool)
                                  or not 0 <= u <= 100):
                errors.append(f"{where}: utilization_pct must be an "
                              f"integer in [0, 100], got {u!r}")
    # critical chains: root-first, time-contiguous joins (child t_emit
    # == parent t_due), same-host depth strictly increasing
    for ci, ch in enumerate(cz.get("chains") or []):
        where = f"causality.chains[{ci}]"
        if not isinstance(ch, dict):
            errors.append(f"{where}: must be an object")
            continue
        ln = ch.get("length")
        if not isinstance(ln, int) or isinstance(ln, bool) or ln < 1:
            errors.append(f"{where}: length must be an integer >= 1")
            continue
        span = ch.get("span_ns")
        if not isinstance(span, int) or isinstance(span, bool) \
                or span < 0:
            errors.append(f"{where}: span_ns must be a non-negative "
                          f"integer, got {span!r}")
        ph = ch.get("per_host") or {}
        if isinstance(ph, dict) and ph \
                and sum(ph.values()) != ln:
            errors.append(f"{where}: per_host counts sum to "
                          f"{sum(ph.values())} but length={ln}")
        pk = ch.get("per_kind") or {}
        if isinstance(pk, dict) and pk \
                and sum(pk.values()) != ln:
            errors.append(f"{where}: per_kind counts sum to "
                          f"{sum(pk.values())} but length={ln}")
        evs = ch.get("events") or []
        if not isinstance(evs, list) or len(evs) > ln:
            errors.append(f"{where}: events must be an array of at "
                          f"most length={ln} records (tail-truncated)")
            continue
        depth_of: dict = {}
        for ei, ev in enumerate(evs):
            w2 = f"{where}.events[{ei}]"
            if not isinstance(ev, dict):
                errors.append(f"{w2}: must be an object")
                continue
            if isinstance(ev.get("t_emit"), int) \
                    and isinstance(ev.get("t_due"), int) \
                    and ev["t_due"] < ev["t_emit"]:
                errors.append(f"{w2}: t_due={ev['t_due']} precedes "
                              f"t_emit={ev['t_emit']} — an event "
                              f"cannot be due before it was emitted")
            if ei > 0 and isinstance(evs[ei - 1], dict):
                prev = evs[ei - 1]
                if isinstance(prev.get("t_due"), int) \
                        and isinstance(ev.get("t_emit"), int) \
                        and ev["t_emit"] != prev["t_due"]:
                    errors.append(
                        f"{w2}: t_emit={ev['t_emit']} breaks the join "
                        f"(parent t_due={prev['t_due']}) — a chain "
                        f"edge requires the child to be emitted at "
                        f"its parent's execution time")
            h = ev.get("host")
            d = ev.get("depth")
            if isinstance(h, int) and isinstance(d, int):
                if h in depth_of and d <= depth_of[h]:
                    errors.append(
                        f"{w2}: depth={d} not strictly greater than "
                        f"the previous depth {depth_of[h]} on host "
                        f"{h} — per-host execution order is total, "
                        f"so same-host chain depth must increase")
                depth_of[h] = d
    # lineage traffic matrix: the cross-host cell sums must cover the
    # cross-host harvested records exactly
    tm = cz.get("traffic_matrix")
    if tm is not None:
        S = cz.get("path_shards")
        if not isinstance(tm, list) or (
                isinstance(S, int) and len(tm) != S) or not all(
                isinstance(row, list)
                and (not isinstance(S, int) or len(row) == S)
                and all(isinstance(c, int) and not isinstance(c, bool)
                        and c >= 0 for c in row)
                for row in tm):
            errors.append("causality.traffic_matrix must be a "
                          "path_shards x path_shards grid of "
                          "non-negative integers")
        elif isinstance(counts.get("cross_host_harvested"), int) \
                and sum(c for row in tm for c in row) \
                != counts["cross_host_harvested"]:
            errors.append(
                f"causality.traffic_matrix sums to "
                f"{sum(c for row in tm for c in row)} but "
                f"cross_host_harvested="
                f"{counts['cross_host_harvested']}")
        # cross-check against the flow recorder (PR 15): both samplers
        # hash the same (time, dst, src, seq) identity, so two
        # LOSSLESS recorders at the SAME period must agree on the
        # cross-shard traffic matrix (warning: bulk-pass emissions
        # bypass the lineage hook, so a bulk-heavy run can diverge
        # legitimately)
        if (isinstance(flows, dict)
                and flows.get("sample_period") == cz.get("sample_period")
                and flows.get("path_shards") == cz.get("path_shards")
                and flows.get("lost_ring") == 0
                and flows.get("lost_window_clamp") == 0
                and cz.get("lost_ring") == 0
                and isinstance(flows.get("traffic_matrix"), list)
                and flows["traffic_matrix"] != tm):
            warnings.append(
                "causality.traffic_matrix disagrees with "
                "flows.traffic_matrix on a lossless run at equal "
                "sample periods — expected only when bulk-pass "
                "events (which bypass the lineage hook) carried "
                "cross-host traffic")
    return errors, warnings


# elastic degradation-ladder actions (faults/supervisor.py
# _elastic_step) — duplicated literally so the lint stays importable
# without the engine
_ELASTIC_ACTIONS = ("retry", "shrink", "serial")


def _is_pow2(n) -> bool:
    return (isinstance(n, int) and not isinstance(n, bool)
            and n >= 1 and not (n & (n - 1)))


def _lint_elastic(el, health) -> tuple[list, list]:
    """(errors, warnings) for an "elastic" block (faults/supervisor.py
    _elastic_block; rides the run manifest and the fleet manifest's
    per-job entries). The invariants are the degradation ladder's
    contract: mesh widths are powers of two that only hold or shrink
    (monotone transitions, contiguous chain), every recorded fault is
    answered by at most one ladder step (losses + divergences ==
    ladder steps, short exactly one when the ladder exhausted),
    mesh_transitions is exactly the width-changing subset of the
    steps, and a divergence's verified frontier can never pass its own
    trip point."""
    errors: list = []
    warnings: list = []
    if not isinstance(el, dict):
        return (["elastic must be an object"], [])
    w = "elastic"
    init, fin = el.get("initial_shards"), el.get("final_shards")
    for k, v in (("initial_shards", init), ("final_shards", fin)):
        if not _is_pow2(v):
            errors.append(f"{w}.{k} must be a positive power of two, "
                          f"got {v!r}")
    if _is_pow2(init) and _is_pow2(fin) and fin > init:
        errors.append(f"{w}: final_shards={fin} exceeds initial_"
                      f"shards={init} — the ladder only holds or "
                      f"shrinks the mesh, never grows it")
    lists = {}
    for k in ("losses", "divergences", "ladder_steps",
              "mesh_transitions"):
        v = el.get(k)
        if not isinstance(v, list):
            errors.append(f"{w}.{k} must be an array")
            lists[k] = []
        else:
            lists[k] = v
    for i, ls in enumerate(lists["losses"]):
        where = f"{w}.losses[{i}]"
        if not isinstance(ls, dict) \
                or ls.get("fault") != "DEVICE_LOST":
            errors.append(f'{where}: must be an object with '
                          f'fault="DEVICE_LOST"')
            continue
        sh = ls.get("shard")
        if not isinstance(sh, int) or isinstance(sh, bool) or sh < -1:
            errors.append(f"{where}: shard must be an integer >= -1 "
                          f"(-1 = unattributed), got {sh!r}")
    for i, dv in enumerate(lists["divergences"]):
        where = f"{w}.divergences[{i}]"
        if not isinstance(dv, dict) \
                or dv.get("fault") != "SHARD_DIVERGENCE":
            errors.append(f'{where}: must be an object with '
                          f'fault="SHARD_DIVERGENCE"')
            continue
        sh = dv.get("shard")
        if not isinstance(sh, int) or isinstance(sh, bool) or sh < 0:
            errors.append(f"{where}: shard must name the offending "
                          f"shard (integer >= 0), got {sh!r}")
        va, ta = dv.get("verified_through_ns"), dv.get("tripped_at_ns")
        for k, v in (("verified_through_ns", va),
                     ("tripped_at_ns", ta)):
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{where}: {k} must be a non-negative "
                              f"integer, got {v!r}")
        if isinstance(va, int) and isinstance(ta, int) \
                and not isinstance(va, bool) \
                and not isinstance(ta, bool) and va >= ta > 0:
            errors.append(
                f"{where}: verified_through_ns={va} reaches its own "
                f"trip point (tripped_at_ns={ta}) — the verified "
                f"frontier stops strictly before the first tripped "
                f"barrier")
    cur = init if _is_pow2(init) else None
    for i, st in enumerate(lists["ladder_steps"]):
        where = f"{w}.ladder_steps[{i}]"
        if not isinstance(st, dict):
            errors.append(f"{where}: must be an object")
            cur = None
            continue
        action = st.get("action")
        if action not in _ELASTIC_ACTIONS:
            errors.append(f"{where}: unknown action {action!r} "
                          f"(expected one of {_ELASTIC_ACTIONS})")
        f_, t_ = st.get("from"), st.get("to")
        if not _is_pow2(f_) or not _is_pow2(t_):
            errors.append(f"{where}: from/to must be positive powers "
                          f"of two, got {f_!r} -> {t_!r}")
            cur = None
            continue
        if action == "retry" and t_ != f_:
            errors.append(f"{where}: a retry holds the mesh, got "
                          f"{f_} -> {t_}")
        if action == "shrink" and t_ >= f_:
            errors.append(f"{where}: a shrink must strictly reduce "
                          f"the width, got {f_} -> {t_}")
        if action == "serial" and t_ != 1:
            errors.append(f"{where}: serial means one shard, got "
                          f"to={t_}")
        if cur is not None and f_ != cur:
            errors.append(f"{where}: from={f_} breaks the chain "
                          f"(previous width {cur}) — ladder steps "
                          f"must be contiguous")
        cur = t_
        rt = st.get("resume_time_ns")
        if not isinstance(rt, int) or isinstance(rt, bool) or rt < 0:
            errors.append(f"{where}: resume_time_ns must be a "
                          f"non-negative integer, got {rt!r}")
    if lists["ladder_steps"] and cur is not None \
            and _is_pow2(fin) and cur != fin:
        errors.append(f"{w}: final_shards={fin} but the last ladder "
                      f"step left the mesh at {cur}")
    want_trans = [s for s in lists["ladder_steps"]
                  if isinstance(s, dict) and s.get("from") != s.get("to")]
    if isinstance(el.get("mesh_transitions"), list) \
            and lists["mesh_transitions"] != want_trans:
        errors.append(
            f"{w}.mesh_transitions must be exactly the width-changing "
            f"subset of ladder_steps ({len(want_trans)} step(s)), got "
            f"{len(lists['mesh_transitions'])}")
    n_faults = len(lists["losses"]) + len(lists["divergences"])
    n_steps = len(lists["ladder_steps"])
    if n_steps > n_faults:
        errors.append(
            f"{w}: {n_steps} ladder step(s) but only {n_faults} "
            f"recorded fault(s) — every step answers exactly one "
            f"loss or divergence")
    elif n_faults - n_steps > 1:
        errors.append(
            f"{w}: {n_faults} fault(s) but only {n_steps} ladder "
            f"step(s) — the ladder answers every fault except, at "
            f"most, the one that exhausted it")
    elif n_faults == n_steps + 1:
        warnings.append(f"{w}: the ladder exhausted on the final "
                        f"fault (the run ended degraded-and-failed; "
                        f"the fleet layer owns the next requeue)")
    sent = (health or {}).get("sentinel") \
        if isinstance(health, dict) else None
    if lists["divergences"] and health is not None and not sent:
        errors.append(
            f"{w}: divergence records but no sentinel block in "
            f"health — a SHARD_DIVERGENCE verdict can only come from "
            f"the integrity sentinel latch")
    return errors, warnings


def _lint_health_sentinel(sent) -> list:
    """Errors for a health block's "sentinel" latch report
    (faults/health.py failure_report): trips never exceed checks, a
    tripped latch names its suspect shard, and the verified frontier
    stops strictly before the first tripped barrier."""
    errors: list = []
    w = "health.sentinel"
    if not isinstance(sent, dict):
        return [f"{w} must be an object"]
    vals = {}
    for k in ("checks", "trips", "tripped_at_ns",
              "verified_through_ns"):
        v = sent.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"{w}.{k} must be a non-negative integer, "
                          f"got {v!r}")
        else:
            vals[k] = v
    sh = sent.get("shard")
    if not isinstance(sh, int) or isinstance(sh, bool) or sh < -1:
        errors.append(f"{w}.shard must be an integer >= -1, got {sh!r}")
    if vals.get("trips", 0) > vals.get("checks", 0):
        errors.append(f"{w}: trips={vals.get('trips')} exceeds "
                      f"checks={vals.get('checks')} — the latch "
                      f"counts a subset of the barrier checks")
    if vals.get("trips"):
        if isinstance(sh, int) and not isinstance(sh, bool) and sh < 0:
            errors.append(f"{w}: a tripped sentinel must name its "
                          f"suspect shard")
        if "tripped_at_ns" in vals and "verified_through_ns" in vals \
                and vals["tripped_at_ns"] > 0 \
                and vals["verified_through_ns"] >= vals["tripped_at_ns"]:
            errors.append(
                f"{w}: verified_through_ns="
                f"{vals['verified_through_ns']} reaches the trip "
                f"point tripped_at_ns={vals['tripped_at_ns']} — a "
                f"tripped barrier is never verified")
    return errors


def lint_checkpoint_elastic(path: str) -> tuple[list, list]:
    """(errors, warnings) for a snapshot's verified-state ledger
    stamp (utils/checkpoint.py elastic_meta / replan_shards). Pure
    numpy + json — no engine import. The invariants: the stamped
    shard_digests list carries exactly one digest per recorded shard,
    last_verified_window never passes the snapshot's own resume time
    (a snapshot cannot be verified past the moment it was taken), and
    every recorded replan is a pow2 -> pow2 restamp."""
    import numpy as np

    errors: list = []
    warnings: list = []
    p = path if path.endswith(".npz") else path + ".npz"
    try:
        z = np.load(p, allow_pickle=False)
    except (OSError, ValueError) as e:
        return ([f"{path}: unreadable npz: {e}"], [])
    with z:
        if "__meta__" not in z.files:
            return ([f"{path}: missing __meta__ — not a snapshot"], [])
        try:
            meta = json.loads(str(z["__meta__"]))
        except ValueError as e:
            return ([f"{path}: __meta__ is not JSON: {e}"], [])
    shards = meta.get("shards")
    t = meta.get("time_ns")
    if not _is_pow2(shards):
        errors.append(f"{path}: __meta__.shards must be a positive "
                      f"power of two, got {shards!r}")
    if not isinstance(t, int) or isinstance(t, bool) or t < 0:
        errors.append(f"{path}: __meta__.time_ns must be a "
                      f"non-negative integer, got {t!r}")
    el = meta.get("elastic")
    if el is None:
        warnings.append(f"{path}: snapshot carries no elastic stamp "
                        f"(no sentinel attached — trusted as-saved)")
        return errors, warnings
    if not isinstance(el, dict):
        return (errors + [f"{path}: __meta__.elastic must be an "
                          f"object"], warnings)
    digs = el.get("shard_digests")
    if not isinstance(digs, list) or not all(
            isinstance(d, str) and d for d in digs):
        errors.append(f"{path}: elastic.shard_digests must be a list "
                      f"of digest strings")
    elif _is_pow2(shards) and len(digs) != shards:
        errors.append(
            f"{path}: elastic.shard_digests holds {len(digs)} "
            f"digest(s) but the snapshot records shards={shards} — "
            f"one digest per shard, exactly")
    lvw = el.get("last_verified_window")
    if lvw is not None:
        if not isinstance(lvw, int) or isinstance(lvw, bool) or lvw < 0:
            errors.append(f"{path}: elastic.last_verified_window must "
                          f"be a non-negative integer or null, got "
                          f"{lvw!r}")
        elif isinstance(t, int) and not isinstance(t, bool) and lvw > t:
            errors.append(
                f"{path}: elastic.last_verified_window={lvw} passes "
                f"the snapshot's own resume time time_ns={t} — a "
                f"snapshot cannot be verified past the moment it was "
                f"taken")
    sent = el.get("sentinel")
    if sent is not None:
        errors += [f"{path}: {m.replace('health.sentinel', 'elastic.sentinel')}"
                   for m in _lint_health_sentinel(sent)]
    for i, rp in enumerate(el.get("replans") or []):
        where = f"{path}: elastic.replans[{i}]"
        if not isinstance(rp, dict) or not _is_pow2(rp.get("from")) \
                or not _is_pow2(rp.get("to")):
            errors.append(f"{where}: must record a pow2 -> pow2 "
                          f"restamp, got {rp!r}")
    return errors, warnings


def _lint_admission(adm) -> tuple[list, list]:
    """(errors, warnings) for an "admission" block — either a resident
    program's lease-table block (fleet/admission.py manifest_block,
    rides the fleet manifest) or the standalone resident run's
    device-plane fold (telemetry/export.py admission_manifest_block,
    rides the run manifest). The core invariant is lease-count
    conservation: every admitted lease is exactly one of completed,
    evicted, quarantined, or still resident — a lease can never
    vanish or be double-counted."""
    errors: list = []
    warnings: list = []
    if not isinstance(adm, dict):
        return (["admission must be an object"], [])
    counts = {}
    for k in ("admitted", "completed", "evicted", "quarantined",
              "resident"):
        v = adm.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"admission.{k} must be a non-negative "
                          f"integer, got {v!r}")
        else:
            counts[k] = v
    for k in ("deferred", "lanes", "lane_width", "admission_events",
              "retraces"):
        v = adm.get(k)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            errors.append(f"admission.{k} must be a non-negative "
                          f"integer, got {v!r}")
    if len(counts) == 5 and counts["admitted"] != (
            counts["completed"] + counts["evicted"]
            + counts["quarantined"] + counts["resident"]):
        errors.append(
            f"lease counts not conserved: admitted="
            f"{counts['admitted']} != completed={counts['completed']} "
            f"+ evicted={counts['evicted']} + quarantined="
            f"{counts['quarantined']} + resident={counts['resident']} "
            f"— every admitted lease must end in exactly one terminal "
            f"state or still hold its lane")
    # zero-retrace contract: a resident program that retraced (or
    # whose program key moved) broke the whole design — admission
    # events must be pure runtime-data mutation
    pk = adm.get("program_key")
    if pk is not None and (not isinstance(pk, str)
                           or not _PROGRAM_KEY.match(pk)):
        errors.append(f'admission.program_key must match "pk" + 16 '
                      f"hex chars, got {pk!r}")
    stable = adm.get("program_key_stable")
    if stable is not None and not isinstance(stable, bool):
        errors.append(f"admission.program_key_stable must be a bool, "
                      f"got {stable!r}")
    elif stable is False:
        errors.append(
            "admission.program_key_stable=false — the program key "
            "moved across an admission event (a join/leave must "
            "never change compiled shapes)")
    rt = adm.get("retraces")
    if isinstance(rt, int) and not isinstance(rt, bool) and rt > 0:
        errors.append(f"admission.retraces={rt} — a resident program "
                      f"must serve every admission event from the one "
                      f"warm trace")
    # degradation ladder: the recorded step must be a real rung and
    # agree with the level index
    lvl = adm.get("degrade_level")
    step = adm.get("degrade_step")
    if lvl is not None and (not isinstance(lvl, int)
                            or isinstance(lvl, bool)
                            or not 0 <= lvl < len(_LEASE_LADDER)):
        errors.append(f"admission.degrade_level must be an integer in "
                      f"[0, {len(_LEASE_LADDER)}), got {lvl!r}")
    if step is not None and step not in _LEASE_LADDER:
        errors.append(f"admission.degrade_step {step!r} is not a "
                      f"ladder rung {_LEASE_LADDER}")
    if (isinstance(lvl, int) and not isinstance(lvl, bool)
            and 0 <= lvl < len(_LEASE_LADDER)
            and step is not None and step != _LEASE_LADDER[lvl]):
        errors.append(f"admission.degrade_step={step!r} disagrees "
                      f"with degrade_level={lvl} "
                      f"({_LEASE_LADDER[lvl]!r})")
    if isinstance(lvl, int) and not isinstance(lvl, bool) and lvl > 0:
        warnings.append(f"admission gate degraded to "
                        f"{_LEASE_LADDER[lvl]!r} (protected-tenant "
                        f"SLO pressure)")
    hist = adm.get("degrade_history")
    if hist is not None:
        if not isinstance(hist, list):
            errors.append("admission.degrade_history must be an array")
        else:
            for i, h in enumerate(hist):
                if not isinstance(h, dict) \
                        or h.get("step") not in _LEASE_LADDER:
                    errors.append(f"admission.degrade_history[{i}] "
                                  f"must name a ladder rung")
    # per-lane lease planes (core/lanes.py admission_report)
    per = adm.get("per_lane")
    active = 0
    if per is not None:
        if not isinstance(per, list):
            errors.append("admission.per_lane must be an array")
            per = []
        nlanes = adm.get("lanes")
        if (isinstance(nlanes, int) and not isinstance(nlanes, bool)
                and per and len(per) != nlanes):
            errors.append(f"admission.per_lane has {len(per)} entries "
                          f"but lanes={nlanes}")
        for i, d in enumerate(per):
            where = f"admission.per_lane[{i}]"
            if not isinstance(d, dict):
                errors.append(f"{where}: must be an object")
                continue
            if d.get("lane") != i:
                errors.append(f"{where}: lane={d.get('lane')!r} out "
                              f"of order (expected {i})")
            for k in ("active", "completed"):
                if not isinstance(d.get(k), bool):
                    errors.append(f"{where}: {k} must be a bool")
            for k in ("epoch", "flushed"):
                v = d.get(k)
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errors.append(f"{where}: {k} must be a "
                                  f"non-negative integer, got {v!r}")
            if d.get("active") is True:
                active += 1
        if per and "resident" in counts and active < counts["resident"]:
            errors.append(
                f"admission: {counts['resident']} resident lease(s) "
                f"but only {active} active device lane plane(s) — a "
                f"live lease must hold an active lane")
    # SLO gate snapshot
    slo = adm.get("slo")
    if slo is not None:
        if not isinstance(slo, dict):
            errors.append("admission.slo must be an object")
            slo = {}
        for k in ("eval_stride", "sustained"):
            v = slo.get(k)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 1):
                errors.append(f"admission.slo.{k} must be an integer "
                              f">= 1, got {v!r}")
        for lane, v in sorted((slo.get("last_p99_ns") or {}).items()):
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"admission.slo.last_p99_ns[{lane}] "
                              f"must be a non-negative integer")
        for job, ratio in sorted((slo.get("breached_jobs")
                                  or {}).items()):
            if (not isinstance(ratio, (int, float))
                    or isinstance(ratio, bool) or ratio <= 1.0):
                errors.append(
                    f"admission.slo.breached_jobs[{job}]={ratio!r} — "
                    f"a recorded breach ratio must exceed 1.0 (p99 "
                    f"over objective), anything else is not a breach")
    if counts.get("evicted"):
        warnings.append(f"{counts['evicted']} lease(s) evicted "
                        f"(SLO shedding or operator churn; salvage "
                        f"artifacts in the lease history)")
    if counts.get("quarantined"):
        warnings.append(f"{counts['quarantined']} lane lease(s) "
                        f"quarantined (lanes stay parked until the "
                        f"program restarts)")
    lw = adm.get("lease_warnings")
    if lw:
        for w in lw:
            warnings.append(f"lease journal: {w}")
    return errors, warnings


def _lint_slo_verdict(slo, flows, where: str) -> list:
    """Errors for one scenario result's "slo" verdict
    (fleet/scenario.py slo_verdict): the verdict must be arithmetic
    over the flow percentiles it claims to summarize."""
    errors: list = []
    if not isinstance(slo, dict):
        return [f"{where} must be an object"]
    obj_ms = slo.get("objective_p99_ms")
    p99 = slo.get("p99_ns")
    met = slo.get("met")
    if (not isinstance(obj_ms, (int, float)) or isinstance(obj_ms, bool)
            or obj_ms <= 0):
        errors.append(f"{where}.objective_p99_ms must be a positive "
                      f"number, got {obj_ms!r}")
    if not isinstance(p99, int) or isinstance(p99, bool) or p99 < 0:
        errors.append(f"{where}.p99_ns must be a non-negative "
                      f"integer, got {p99!r}")
    if not isinstance(met, bool):
        errors.append(f"{where}.met must be a bool, got {met!r}")
    tc = slo.get("tenant_class")
    if tc is not None and tc not in ("protected", "best_effort"):
        errors.append(f"{where}.tenant_class must be 'protected' or "
                      f"'best_effort', got {tc!r}")
    if not errors and met != (p99 <= obj_ms * 1e6):
        errors.append(
            f"{where}: met={met} contradicts p99_ns={p99} vs "
            f"objective {obj_ms}ms ({int(obj_ms * 1e6)}ns) — the "
            f"verdict must be arithmetic over its own numbers")
    # the claimed p99 must be the worst per-lane flow p99 it
    # summarizes (slo_verdict takes the max across lanes)
    per_lane = (flows or {}).get("per_lane")
    if isinstance(per_lane, dict) and per_lane \
            and isinstance(p99, int) and not isinstance(p99, bool):
        worst = max((int(d.get("p99_ns", 0) or 0)
                     for d in per_lane.values()
                     if isinstance(d, dict) and d.get("count")),
                    default=None)
        if worst is not None and p99 != worst:
            errors.append(
                f"{where}.p99_ns={p99} but the flow per-lane "
                f"percentiles peak at {worst} — the verdict must "
                f"summarize the flow block it rides with")
    return errors


# sweep block (sweep/driver.py sweep_block): the ranking logic is
# duplicated literally from sweep/reduce.py so the lint can RE-DERIVE
# every recorded table and prune decision from the per-job entries
# without importing the engine — a recorded ranking that disagrees
# with its own inputs is tampering or a writer bug, not a style issue
_SWEEP_METRICS = ("flow_p50_ns", "flow_p95_ns", "flow_p99_ns",
                  "drops", "events", "events_per_sec")
_SWEEP_ELIGIBLE = ("ok", "warnings")
_SWEEP_CATS = ("completed", "failed", "quarantined", "pruned",
               "pending")


def _sweep_metric_value(entry, metric):
    """Mirror of sweep/reduce.py metric_value over one fleet-manifest
    job entry; None when the job carries no data for the metric."""
    result = entry.get("result") or {}
    counters = result.get("counters") or {}
    if metric == "events":
        v = counters.get("events_processed")
        return None if v is None else int(v)
    if metric == "drops":
        v = counters.get("drops_total")
        return None if v is None else int(v)
    if metric == "events_per_sec":
        v = result.get("events_per_sec")
        return None if v is None else float(v)
    pkey = {"flow_p50_ns": "p50_ns", "flow_p95_ns": "p95_ns",
            "flow_p99_ns": "p99_ns"}.get(metric)
    if pkey is None:
        return None
    per_lane = (result.get("flows") or {}).get("per_lane") or {}
    vals = [int(s.get(pkey, 0)) for s in per_lane.values()
            if isinstance(s, dict)
            and int(s.get("count", 0) or 0) > 0]
    return max(vals) if vals else None


def _sweep_rank(entries, objective):
    """Mirror of sweep/reduce.py rank: eligible rows by (value, point)
    under the objective's goal, ineligible rows after in point order."""
    need_clean = bool(objective.get("require_clean_health"))
    eligible, rest = [], []
    for pid in sorted(entries):
        entry = entries[pid]
        status = entry.get("status")
        if status in ("failed", "quarantined"):
            verdict = status
        elif status != "done":
            verdict = "pending"
        else:
            hv = (entry.get("result") or {}).get("health_verdict")
            if hv is not None and hv != "clean":
                verdict = "unhealthy" if need_clean else "warnings"
            else:
                verdict = "ok"
        value = (_sweep_metric_value(entry, objective.get("metric"))
                 if verdict in _SWEEP_ELIGIBLE else None)
        if verdict in _SWEEP_ELIGIBLE and value is None:
            verdict = "no_data"
        row = {"point": pid, "value": value, "verdict": verdict}
        (eligible if verdict in _SWEEP_ELIGIBLE else rest).append(row)
    sign = 1 if objective.get("goal") == "min" else -1
    eligible.sort(key=lambda r: (sign * r["value"], r["point"]))
    return eligible + rest


def _lint_sweep(sw, jobs) -> tuple[list, list]:
    """(errors, warnings) for a fleet manifest's "sweep" roll-up
    (sweep/driver.py sweep_block). The three core invariants:

      1. lattice conservation — every expanded point ends in exactly
         one of completed / failed / quarantined / pruned / pending,
         and a complete sweep has no pending points;
      2. ranking re-derivation — every recorded per-round ranking
         (and the final table, and "best") must re-derive from the
         per-job result blocks it claims to summarize;
      3. program-key census vs the prewarm log — every sweep job's
         affinity key is in the planned census, the census counts sum
         to the jobs expanded, and every realized program key was in
         the prewarm log (warning: the pool compiled something the
         census did not predict)."""
    errors: list = []
    warnings: list = []
    if not isinstance(sw, dict):
        return (["sweep must be an object"], [])
    if not isinstance(sw.get("id"), str) or not sw.get("id"):
        errors.append("sweep.id must be a non-empty string")
    obj = sw.get("objective")
    if not isinstance(obj, dict) \
            or obj.get("metric") not in _SWEEP_METRICS \
            or obj.get("goal") not in ("min", "max"):
        errors.append(f"sweep.objective must name a metric in "
                      f"{_SWEEP_METRICS} and a goal in "
                      f"('min', 'max'), got {obj!r}")
        obj = None
    lattice = sw.get("lattice")
    if not isinstance(lattice, int) or isinstance(lattice, bool) \
            or lattice < 1:
        errors.append(f"sweep.lattice must be a positive integer, "
                      f"got {lattice!r}")
        lattice = None
    rounds = sw.get("rounds")
    if not isinstance(rounds, list) or not rounds \
            or not all(isinstance(r, dict) for r in rounds):
        errors.append('sweep.rounds must be a non-empty array of '
                      'round objects')
        return errors, warnings
    # lattice conservation
    pts = sw.get("points")
    counts = {}
    if not isinstance(pts, dict):
        errors.append("sweep.points must be an object")
    else:
        for k in ("expanded",) + _SWEEP_CATS:
            v = pts.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"sweep.points.{k} must be a "
                              f"non-negative integer, got {v!r}")
            else:
                counts[k] = v
        if len(counts) == 6 and counts["expanded"] != sum(
                counts[c] for c in _SWEEP_CATS):
            errors.append(
                f"lattice not conserved: expanded="
                f"{counts['expanded']} != completed="
                f"{counts['completed']} + failed={counts['failed']} "
                f"+ quarantined={counts['quarantined']} + pruned="
                f"{counts['pruned']} + pending={counts['pending']} — "
                f"every expanded point must end in exactly one "
                f"category")
        if sw.get("complete") and counts.get("pending"):
            errors.append(f"sweep claims complete but "
                          f"{counts['pending']} point(s) are pending")
        if lattice is not None and "expanded" in counts \
                and counts["expanded"] > lattice:
            errors.append(f"sweep.points.expanded="
                          f"{counts['expanded']} exceeds the lattice "
                          f"({lattice})")
        r0 = rounds[0].get("points")
        if isinstance(r0, list) and "expanded" in counts \
                and len(r0) != counts["expanded"]:
            errors.append(f"sweep.points.expanded="
                          f"{counts['expanded']} but round 0 planned "
                          f"{len(r0)} point(s)")
    # per-round: job linkage, count re-derivation, ranking
    # re-derivation from the per-job entries
    expanded_jobs = 0
    search = sw.get("search") if isinstance(sw.get("search"), dict) \
        else {}
    for k, rd in enumerate(rounds):
        where = f"sweep.rounds[{k}]"
        if rd.get("round") != k:
            errors.append(f"{where}: round={rd.get('round')!r} out of "
                          f"order (expected {k})")
        rpts = rd.get("points")
        if not isinstance(rpts, list) or not rpts:
            errors.append(f"{where}: points must be a non-empty array")
            continue
        expanded_jobs += len(rpts)
        entries = {}
        rcounts = {"done": 0, "failed": 0, "quarantined": 0,
                   "pending": 0}
        for pid in rpts:
            jid = f"r{k}-{pid}"
            j = jobs.get(jid)
            if not isinstance(j, dict):
                rcounts["pending"] += 1
                entries[pid] = {}
                continue
            entries[pid] = j
            st = j.get("status")
            rcounts[st if st in rcounts else "pending"] += 1
        rc = rd.get("counts")
        if isinstance(rc, dict) and rc != rcounts:
            errors.append(f"{where}.counts={rc} but the job statuses "
                          f"fold to {rcounts}")
        table = rd.get("ranking")
        if table is None:
            continue
        if not isinstance(table, list):
            errors.append(f"{where}.ranking must be an array")
            continue
        if obj is not None:
            want = _sweep_rank(entries, obj)
            if table != want:
                errors.append(
                    f"{where}.ranking does not re-derive from the "
                    f"per-job result blocks — recorded {table!r} vs "
                    f"derived {want!r} (the reducer is pure; a "
                    f"divergence means the table was not computed "
                    f"from these results)")
        # successive halving: round k+1's survivors and prune set
        # must be THE deterministic function of round k's table —
        # top ceil(n_eligible/eta), never below one survivor
        if search.get("strategy") == "halving" and k + 1 < len(rounds):
            eta = search.get("eta")
            eta = eta if isinstance(eta, int) \
                and not isinstance(eta, bool) and eta >= 2 else 2
            elig = [r.get("point") for r in table
                    if isinstance(r, dict)
                    and r.get("verdict") in _SWEEP_ELIGIBLE]
            keep = max(1, -(-len(elig) // eta))
            survive = elig[:keep]
            nxt = rounds[k + 1]
            if nxt.get("points") != survive:
                errors.append(
                    f"sweep.rounds[{k + 1}].points="
                    f"{nxt.get('points')!r} but round {k} ranking "
                    f"keeps {survive!r} (top ceil({len(elig)}/{eta})) "
                    f"— a halving round must re-derive from the "
                    f"journaled reduce output")
            want_pruned = sorted(set(elig) - set(survive))
            if sorted(nxt.get("pruned") or []) != want_pruned:
                errors.append(
                    f"sweep.rounds[{k + 1}].pruned="
                    f"{nxt.get('pruned')!r} but round {k} ranking "
                    f"prunes {want_pruned!r}")
    je = sw.get("jobs_expanded")
    if je is not None and je != expanded_jobs:
        errors.append(f"sweep.jobs_expanded={je!r} but the rounds "
                      f"planned {expanded_jobs} job(s)")
    # final table and best must restate the last reduced round
    final = next((rd.get("ranking") for rd in reversed(rounds)
                  if rd.get("ranking") is not None), None)
    if sw.get("ranking") != final:
        errors.append("sweep.ranking does not match the last reduced "
                      "round's table")
    if isinstance(final, list):
        top = next((r.get("point") for r in final
                    if isinstance(r, dict)
                    and r.get("verdict") in _SWEEP_ELIGIBLE), None)
        if sw.get("best") != top:
            errors.append(f"sweep.best={sw.get('best')!r} but the "
                          f"final ranking's top eligible point is "
                          f"{top!r}")
    # distinct-program census vs the sweep's jobs and the prewarm log
    census = sw.get("census")
    sweep_jobs = {jid: j for jid, j in sorted(jobs.items())
                  if isinstance(j, dict)
                  and re.match(r"^r\d+-p\d+$", jid)}
    if not isinstance(census, dict) \
            or not isinstance(census.get("programs"), dict):
        errors.append('sweep.census must carry a "programs" object')
    else:
        programs = census["programs"]
        for ak, n in sorted(programs.items()):
            if not _AFFINITY_KEY.match(ak):
                errors.append(f'sweep.census.programs key {ak!r} must '
                              f'match "ak" + 16 hex chars')
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                errors.append(f"sweep.census.programs[{ak}]={n!r} "
                              f"must be a positive point count")
        if census.get("distinct") != len(programs):
            errors.append(f"sweep.census.distinct="
                          f"{census.get('distinct')!r} but "
                          f"{len(programs)} program(s) listed")
        total = sum(n for n in programs.values()
                    if isinstance(n, int) and not isinstance(n, bool))
        if total != expanded_jobs:
            errors.append(f"sweep.census counts sum to {total} but "
                          f"the rounds planned {expanded_jobs} "
                          f"job(s) — the census must partition the "
                          f"lattice")
        for jid, j in sweep_jobs.items():
            ak = j.get("affinity_key")
            if isinstance(ak, str) and ak not in programs:
                errors.append(f"jobs[{jid}].affinity_key {ak} is not "
                              f"in the sweep census — the plan must "
                              f"predict every program the pool loads")
    pw = sw.get("prewarm")
    if pw is not None:
        if not isinstance(pw, dict):
            errors.append("sweep.prewarm must be an object")
        else:
            for k in ("hits", "compiled"):
                v = pw.get(k)
                if not isinstance(v, int) or isinstance(v, bool) \
                        or v < 0:
                    errors.append(f"sweep.prewarm.{k} must be a "
                                  f"non-negative integer, got {v!r}")
            keys = pw.get("keys")
            if not isinstance(keys, list):
                errors.append("sweep.prewarm.keys must be an array")
                keys = []
            for pk in keys:
                if not isinstance(pk, str) \
                        or not _PROGRAM_KEY.match(pk):
                    errors.append(f'sweep.prewarm.keys entry {pk!r} '
                                  f'must match "pk" + 16 hex chars')
            warmed = {pk for pk in keys if isinstance(pk, str)}
            cold = sorted({j["program_key"]
                           for j in sweep_jobs.values()
                           if isinstance(j.get("program_key"), str)
                           and j["program_key"] not in warmed})
            if cold:
                warnings.append(
                    f"sweep jobs realized program key(s) the prewarm "
                    f"log never warmed: {cold} — the pool compiled "
                    f"cold (census prediction diverged from the "
                    f"build?)")
    return errors, warnings


def lint_salvage(path: str) -> list:
    """Errors for a lane-salvage artifact (utils/checkpoint.py
    save_salvage; faults/escalate.py extract_lane output). Pure
    numpy — no engine import — so the soak and CI can lint salvage
    evidence anywhere. Returns [] when clean."""
    import zlib

    import numpy as np

    errors: list = []
    try:
        z = np.load(path, allow_pickle=False)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable npz: {e}"]
    with z:
        if "__meta__" not in z.files:
            return [f"{path}: missing __meta__ — not a salvage "
                    f"artifact"]
        try:
            meta = json.loads(str(z["__meta__"]))
        except ValueError as e:
            return [f"{path}: __meta__ is not JSON: {e}"]
        if meta.get("kind") != "lane_salvage":
            errors.append(f"{path}: kind={meta.get('kind')!r}, "
                          f"expected 'lane_salvage' (a resumable "
                          f"snapshot is not salvage evidence)")
        leaves = sorted(k for k in z.files if k != "__meta__")
        if not leaves:
            errors.append(f"{path}: artifact holds zero state leaves")
        keys = meta.get("keys")
        if isinstance(keys, list) and sorted(keys) != leaves:
            errors.append(f"{path}: __meta__.keys disagrees with the "
                          f"stored leaves")
        crcs = meta.get("crc32")
        if not isinstance(crcs, dict):
            errors.append(f"{path}: missing per-leaf crc32 map")
            crcs = {}
        for k in leaves:
            arr = z[k]
            if k in crcs and (zlib.crc32(
                    np.ascontiguousarray(arr).tobytes())
                    & 0xFFFFFFFF) != crcs[k]:
                errors.append(f"{path}: leaf {k} fails its CRC32 — "
                              f"salvage evidence is corrupt")
        t = meta.get("time_ns")
        if not isinstance(t, int) or isinstance(t, bool) or t < 0:
            errors.append(f"{path}: __meta__.time_ns must be a "
                          f"non-negative integer, got {t!r}")
        caps = meta.get("capacities")
        if not isinstance(caps, dict) or not caps.get("num_hosts"):
            errors.append(f"{path}: __meta__.capacities must name the "
                          f"slice's shapes (at least num_hosts)")
    return errors


def lint_trace_obj(obj) -> tuple[list, list]:
    """(errors, warnings) for a parsed Chrome-trace object."""
    errors: list = []
    warnings: list = []
    if not isinstance(obj, dict) or "traceEvents" not in obj:
        return (['top level must be an object with "traceEvents" '
                 '(the JSON Object Format; Perfetto rejects bare '
                 'arrays with displayTimeUnit)'], [])
    evs = obj["traceEvents"]
    if not isinstance(evs, list):
        return (['"traceEvents" must be an array'], [])
    windows = []
    for i, e in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict) or "ph" not in e:
            errors.append(f'{where}: every event needs a "ph" phase')
            continue
        ph = e["ph"]
        if ph == "M":
            if e.get("name") not in KNOWN_METADATA:
                warnings.append(
                    f'{where}: metadata name {e.get("name")!r} is not '
                    f'one the viewers understand ({sorted(KNOWN_METADATA)})')
            continue
        if ph == "C":
            # counter events (the critical-path track's per-window
            # jump-utilization series, export.py pid 3): need a name,
            # a numeric ts, and a numeric-valued args series
            if not e.get("name"):
                errors.append(f'{where}: "C" event needs a name')
            if not isinstance(e.get("ts"), (int, float)):
                errors.append(f'{where}: "C" event needs numeric ts')
            a = e.get("args")
            if not isinstance(a, dict) or not a:
                errors.append(f'{where}: "C" event needs a non-empty '
                              f'args object (the counter series)')
            continue
        if ph != "X":
            warnings.append(f'{where}: unexpected phase {ph!r} (the '
                            f'exporter only emits "X", "C" and "M")')
            continue
        for k in ("ts", "dur"):
            if not isinstance(e.get(k), (int, float)):
                errors.append(f'{where}: "X" event needs numeric {k}')
        for k in ("pid", "tid"):
            if not isinstance(e.get(k), int):
                errors.append(f'{where}: "X" event needs integer {k}')
        if isinstance(e.get("dur"), (int, float)) and e["dur"] <= 0:
            errors.append(f'{where}: dur must be > 0 (zero-duration '
                          f'complete events render invisibly)')
        if e.get("pid") == 0 and isinstance(e.get("args"), dict):
            a = e["args"]
            for k in WINDOW_ARGS:
                if k in a and (not isinstance(a[k], int) or a[k] < 0):
                    errors.append(f"{where}: args.{k} must be a "
                                  f"non-negative integer")
            q = a.get("queue_occupancy")
            if isinstance(q, dict) and (
                    q.get("min", 0) > q.get("max", 0)):
                errors.append(f"{where}: queue_occupancy min > max")
            if isinstance(e.get("ts"), (int, float)):
                windows.append((e["ts"], e.get("dur", 0), i))
    # window ordering: the harvester emits records in ring order, so
    # an unsorted sim-time track means export corruption; gaps are
    # legal (ring overrun drops whole records, latched elsewhere)
    last_end = None
    for ts, dur, i in windows:
        if last_end is not None and ts < last_end:
            warnings.append(
                f"traceEvents[{i}]: sim-time window at ts={ts} starts "
                f"before the previous window ended ({last_end}) — "
                f"overlapping windows (supervisor replay after a "
                f"resume can legally do this; otherwise suspect)")
        last_end = ts + dur
    if not windows:
        warnings.append("no sim-time window events (pid 0) — empty "
                        "run or telemetry was off")
    return errors, warnings


def lint_manifest_obj(man) -> tuple[list, list]:
    """(errors, warnings) for a parsed run_manifest.json."""
    errors: list = []
    warnings: list = []
    if not isinstance(man, dict):
        return (["manifest must be a JSON object"], [])
    for k in ("config_hash", "seed", "shards", "counters"):
        if k not in man:
            errors.append(f'manifest missing "{k}"')
    tel = man.get("telemetry")
    if not isinstance(tel, dict):
        errors.append('manifest missing the "telemetry" block')
        return errors, warnings
    lost = tel.get("records_lost", 0)
    if lost:
        # the loss MUST be surfaced: either the health block carries
        # the latch or a diagnostic names it — never a silent integer
        health = man.get("health", {})
        latched = health.get("telemetry_lost", 0) == lost or any(
            "telemetry ring overran" in d
            for d in health.get("diagnostics", []))
        if not latched:
            errors.append(
                f"telemetry.records_lost={lost} but the health block "
                f"does not surface it — ring overruns must be latched "
                f"(faults/health.py), never silent")
        else:
            warnings.append(
                f"{lost} telemetry record(s) lost to ring overrun "
                f"(latched in health; trace has gaps)")
    rec = tel.get("windows_recorded", 0)
    cw = man.get("counters", {}).get("windows")
    if cw is not None and rec + lost > cw:
        errors.append(
            f"telemetry accounts for {rec}+{lost} windows but the "
            f"engine ran only {cw}")
    # compile accounting (VERDICT open item 6, first step): a bench /
    # CLI manifest that carries compile_s must make it a sane number,
    # and the fresh-vs-cache flag a bool
    cs = man.get("compile_s")
    if cs is not None and (not isinstance(cs, (int, float))
                           or isinstance(cs, bool) or cs < 0):
        errors.append(f"compile_s must be a non-negative number, "
                      f"got {cs!r}")
    cf = man.get("compile_fresh")
    if cf is not None and not isinstance(cf, bool):
        errors.append(f"compile_fresh must be a bool, got {cf!r}")
    # program-store accounting block (optional): the AOT warm-serving
    # record (compile/serve.py), with the bench's warm-up call nested
    # under "warmup" for one-row fresh-vs-cached scoring
    comp = man.get("compile")
    if comp is not None:
        e2, w2 = _lint_compile_block(comp, "compile")
        errors += e2
        warnings += w2
        if isinstance(comp, dict) and comp.get("warmup") is not None:
            e2, w2 = _lint_compile_block(comp["warmup"],
                                         "compile.warmup")
            errors += e2
            warnings += w2
    # sparse fast-path counters: non-negative, and hit+miss can never
    # exceed the windows the engine ran
    ctr = man.get("counters", {})
    fp = [ctr.get(k) for k in ("fastpath_hit", "fastpath_miss")]
    for k, v in zip(("fastpath_hit", "fastpath_miss"), fp):
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            errors.append(f"counters.{k} must be a non-negative "
                          f"integer, got {v!r}")
    if (cw is not None and all(isinstance(v, int) for v in fp)
            and fp[0] + fp[1] > cw):
        errors.append(
            f"fastpath_hit+miss = {fp[0]}+{fp[1]} exceeds the "
            f"{cw} windows the engine ran")
    # dual-mode conformance block (optional): counts must be coherent
    # non-negative ints summing to the per-workload verdicts, and a
    # divergence is always SURFACED as a warning
    conf = man.get("conformance")
    if conf is not None:
        if not isinstance(conf, dict):
            errors.append("conformance must be an object")
        else:
            for k in ("workloads", "agree", "diverge", "total"):
                if k not in conf:
                    errors.append(f'conformance missing "{k}"')
            for k in ("agree", "diverge", "total"):
                v = conf.get(k)
                if k in conf and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 0):
                    errors.append(f"conformance.{k} must be a "
                                  f"non-negative integer, got {v!r}")
            wl = conf.get("workloads")
            if isinstance(wl, dict) and all(
                    isinstance(conf.get(k), int)
                    for k in ("agree", "diverge", "total")):
                if conf["agree"] + conf["diverge"] != conf["total"] \
                        or conf["total"] != len(wl):
                    errors.append(
                        f"conformance counts incoherent: agree="
                        f"{conf['agree']} + diverge={conf['diverge']} "
                        f"vs total={conf['total']} over "
                        f"{len(wl)} workload verdict(s)")
            if isinstance(conf.get("diverge"), int) and conf["diverge"]:
                bad = sorted(k for k, v in (wl or {}).items()
                             if v != "agree")
                warnings.append(
                    f"conformance: {conf['diverge']} workload(s) "
                    f"diverged between backends: {bad}")
    # compile-time specialization block (optional): vector/dropped
    # coherence, key derivation, guard-latch fatality
    spec = man.get("specialization")
    if spec is not None:
        e2, w2 = _lint_specialization(spec, man.get("counters"),
                                      man.get("health"))
        errors += e2
        warnings += w2
    # supervisor chain identity (optional): run_id / resume_of are
    # opaque id strings; a resume_of without a run_id is incoherent
    for k in ("run_id", "resume_of"):
        v = man.get(k)
        if v is not None and (not isinstance(v, str) or not v):
            errors.append(f"{k} must be a non-empty string, got {v!r}")
    if man.get("resume_of") is not None and man.get("run_id") is None:
        errors.append('manifest carries "resume_of" without "run_id" '
                      '— a chained run must identify itself')
    # escalation records (optional): the supervisor's healed capacity
    # trips. Each names a known grow knob, grows strictly (from < to),
    # and a run that escalated and ended clean must show zero on the
    # latch counter it healed — a surviving overflow means the heal
    # lied.
    esc = man.get("escalations")
    if esc is not None:
        if not isinstance(esc, list) or not esc:
            errors.append("escalations must be a non-empty array "
                          "(omit the key for runs that never healed)")
            esc = []
        known_knobs = {"event_capacity", "outbox_capacity",
                       "router_ring"}
        latch_of_knob = {"event_capacity": "events_overflow",
                         "outbox_capacity": "outbox_overflow",
                         "router_ring": "rq_overflow"}
        ctr = man.get("counters", {})
        verdict = man.get("health", {}).get("verdict")
        for i, e in enumerate(esc):
            where = f"escalations[{i}]"
            if not isinstance(e, dict):
                errors.append(f"{where}: must be an object")
                continue
            for k in ("time_ns", "latch", "knob", "from", "to"):
                if k not in e:
                    errors.append(f'{where}: missing "{k}"')
            for k in ("time_ns", "from", "to"):
                v = e.get(k)
                if k in e and (not isinstance(v, int)
                               or isinstance(v, bool) or v < 0):
                    errors.append(f"{where}: {k} must be a "
                                  f"non-negative integer, got {v!r}")
            knob = e.get("knob")
            if knob is not None and knob not in known_knobs:
                errors.append(f"{where}: unknown grow knob {knob!r} "
                              f"(expected one of {sorted(known_knobs)})")
            if (isinstance(e.get("from"), int)
                    and isinstance(e.get("to"), int)
                    and e["to"] <= e["from"]):
                errors.append(f"{where}: capacities only grow — "
                              f"from={e['from']} to={e['to']}")
            latch = latch_of_knob.get(knob)
            if (latch and verdict == "clean"
                    and isinstance(ctr.get(latch), int)
                    and ctr[latch] != 0):
                errors.append(
                    f"{where}: run escalated {knob} and reports a "
                    f"clean verdict, yet counters.{latch}="
                    f"{ctr[latch]} — the healed run must end with "
                    f"the latch at zero")
        if esc:
            warnings.append(
                f"{len(esc)} capacity escalation(s) healed this run "
                f"(final capacities grew; see escalations[])")
    pre = man.get("preempted")
    if pre is not None and not isinstance(pre, bool):
        errors.append(f"preempted must be a bool, got {pre!r}")
    # dispatch block (optional): the chunked window loop's shape.
    # windows_per_dispatch >= 1, dispatches >= 0, and when the
    # per-dispatch "windows" list is present (clean single-attempt
    # non-resumed runs only) each entry fits the chunk and the sum
    # equals the engine's executed-window counter exactly.
    disp = man.get("dispatch")
    if disp is not None:
        if not isinstance(disp, dict):
            errors.append("dispatch must be an object")
        else:
            wpd = disp.get("windows_per_dispatch")
            if (not isinstance(wpd, int) or isinstance(wpd, bool)
                    or wpd < 1):
                errors.append(f"dispatch.windows_per_dispatch must be "
                              f"an integer >= 1, got {wpd!r}")
            nd = disp.get("dispatches")
            if (not isinstance(nd, int) or isinstance(nd, bool)
                    or nd < 0):
                errors.append(f"dispatch.dispatches must be a "
                              f"non-negative integer, got {nd!r}")
            dw = disp.get("windows")
            if dw is not None:
                if not isinstance(dw, list) or not all(
                        isinstance(w, int) and not isinstance(w, bool)
                        and w >= 0 for w in dw):
                    errors.append("dispatch.windows must be a list of "
                                  "non-negative integers")
                else:
                    if isinstance(nd, int) and len(dw) != nd:
                        errors.append(
                            f"dispatch.windows has {len(dw)} entries "
                            f"but dispatches={nd}")
                    if isinstance(wpd, int) and any(
                            w > wpd for w in dw):
                        errors.append(
                            f"dispatch.windows entry exceeds "
                            f"windows_per_dispatch={wpd}: {dw}")
                    if cw is not None and sum(dw) != cw:
                        errors.append(
                            f"dispatch.windows sums to {sum(dw)} but "
                            f"counters.windows={cw} — per-dispatch "
                            f"accounting must cover every executed "
                            f"window exactly")
            aj = disp.get("adaptive_jump_mean_ns")
            if aj is not None and (
                    not isinstance(aj, (int, float))
                    or isinstance(aj, bool) or aj < 0):
                errors.append(f"dispatch.adaptive_jump_mean_ns must "
                              f"be a non-negative number, got {aj!r}")
    # injection block (optional): open-system traffic accounting
    # (inject/__init__.py manifest_block). The device latches must be
    # coherent ints, drops must be SURFACED in health (latch design:
    # never a silent integer), the per-window telemetry plane must sum
    # to the device total when no records were lost, and the feeder's
    # reconciliation must close: every trace event is injected,
    # dropped, or deferred past end-of-run — nothing vanishes.
    inj = man.get("injection")
    if inj is not None:
        if not isinstance(inj, dict):
            errors.append("injection must be an object")
            inj = {}
        for k in ("lanes", "injected", "dropped", "late"):
            v = inj.get(k)
            if (not isinstance(v, int) or isinstance(v, bool)
                    or v < 0):
                errors.append(f"injection.{k} must be a non-negative "
                              f"integer, got {v!r}")
        lanes = inj.get("lanes")
        if isinstance(lanes, int) and lanes >= 1 \
                and lanes & (lanes - 1):
            errors.append(f"injection.lanes must be a power of two "
                          f"(slot = trace position % lanes), got "
                          f"{lanes}")
        health = man.get("health", {})
        dropped = inj.get("dropped")
        if isinstance(dropped, int) and dropped:
            latched = health.get("inject_dropped", 0) == dropped \
                or any("injection drops" in d
                       for d in health.get("diagnostics", []))
            if not latched:
                errors.append(
                    f"injection.dropped={dropped} but the health "
                    f"block does not surface it — refused injections "
                    f"must be latched (faults/health.py), never "
                    f"silent")
            else:
                warnings.append(
                    f"{dropped} injected event(s) dropped by full "
                    f"host rows (latched in health; results are "
                    f"missing those trace events)")
        late = inj.get("late")
        if isinstance(late, int) and late:
            errors.append(
                f"injection.late={late}: events merged after their "
                f"window had run — the feeder's horizon contract "
                f"was violated (timestamps perturbed)")
        # per-window plane vs device latch: lossless telemetry must
        # account for every injected event window by window
        if (tel.get("records_lost", 0) == 0
                and isinstance(tel.get("injected_sum"), int)
                and isinstance(inj.get("injected"), int)
                and tel["injected_sum"] != inj["injected"]):
            errors.append(
                f"telemetry.injected_sum={tel['injected_sum']} but "
                f"injection.injected={inj['injected']} with zero "
                f"records lost — the per-window plane must sum to "
                f"the device latch")
        # feeder reconciliation (only defined once the trace drained
        # and latched its total)
        te = inj.get("trace_events")
        dfr = inj.get("deferred")
        if isinstance(te, int) and isinstance(dfr, int) and all(
                isinstance(inj.get(k), int)
                for k in ("injected", "dropped")):
            if inj["injected"] + inj["dropped"] + dfr != te:
                errors.append(
                    f"injection does not reconcile: injected="
                    f"{inj['injected']} + dropped={inj['dropped']} + "
                    f"deferred={dfr} != trace_events={te} — every "
                    f"trace event must be injected, dropped, or "
                    f"deferred, never silently lost")
            if dfr:
                warnings.append(
                    f"{dfr} trace event(s) deferred past end-of-run "
                    f"(timestamps beyond the simulation horizon)")
        bp = inj.get("backpressure")
        if bp is not None and (not isinstance(bp, int)
                               or isinstance(bp, bool) or bp < 0):
            errors.append(f"injection.backpressure must be a "
                          f"non-negative integer, got {bp!r}")
        elif isinstance(bp, int) and bp:
            warnings.append(
                f"feeder hit backpressure on {bp} refill(s) — the "
                f"staging buffer filled; raise --inject-lanes if "
                f"wallclock suffers")
    # lanes block (optional): lane-isolated packed-run accounting
    # (telemetry/export.py lanes_manifest_block). The per-lane counters
    # are [R] companion planes of the run-total latches, accumulated in
    # lockstep with the scalars — each latch's lane shares must sum to
    # the run total EXACTLY (the scalars stay authoritative). Every
    # quarantined lane must be fully described (trip names, quarantine
    # time), and when the supervisor's lane surgery ran, carry its
    # salvage pointer + requeue context.
    lb = man.get("lanes")
    if lb is not None:
        if not isinstance(lb, dict):
            errors.append("lanes must be an object")
            lb = {}
        nlanes = lb.get("replicas")
        if (not isinstance(nlanes, int) or isinstance(nlanes, bool)
                or nlanes < 1):
            errors.append(f"lanes.replicas must be an integer >= 1, "
                          f"got {nlanes!r}")
            nlanes = None
        if not isinstance(lb.get("contained"), bool):
            errors.append("lanes.contained must be a bool")
        per = lb.get("per_lane")
        if not isinstance(per, list) or not per:
            errors.append("lanes.per_lane must be a non-empty array")
            per = []
        if nlanes is not None and per and len(per) != nlanes:
            errors.append(f"lanes.per_lane has {len(per)} entries but "
                          f"replicas={nlanes}")
        quar = lb.get("quarantined")
        if not isinstance(quar, list) or not all(
                isinstance(q, int) and not isinstance(q, bool)
                for q in quar):
            errors.append("lanes.quarantined must be a list of lane "
                          "indices")
            quar = []
        lane_counts = ("events_overflow", "outbox_overflow",
                       "rq_overflow", "inj_dropped", "stall_streak",
                       "time_regression", "events_exec", "flushed")
        sums = dict.fromkeys(lane_counts, 0)
        rows_ok = bool(per)
        seen_quar = []
        for i, d in enumerate(per):
            where = f"lanes.per_lane[{i}]"
            if not isinstance(d, dict):
                errors.append(f"{where}: must be an object")
                rows_ok = False
                continue
            if d.get("lane") != i:
                errors.append(f"{where}: lane={d.get('lane')!r} out "
                              f"of order (expected {i})")
            for k in lane_counts:
                v = d.get(k)
                if (not isinstance(v, int) or isinstance(v, bool)
                        or v < 0):
                    errors.append(f"{where}: {k} must be a "
                                  f"non-negative integer, got {v!r}")
                    rows_ok = False
                else:
                    sums[k] += v
            if d.get("quarantined"):
                seen_quar.append(i)
                for k in ("quarantined_at_ns", "trip_bits"):
                    if not isinstance(d.get(k), int):
                        errors.append(f"{where}: quarantined lane "
                                      f"must carry {k}")
                if not d.get("trip"):
                    errors.append(f"{where}: quarantined lane must "
                                  f"name its trip(s)")
        if per and sorted(quar) != seen_quar:
            errors.append(f"lanes.quarantined={sorted(quar)} disagrees "
                          f"with the per-lane quarantined flags "
                          f"({seen_quar})")
        if rows_ok:
            for k in ("events_overflow", "outbox_overflow",
                      "rq_overflow"):
                total = ctr.get(k)
                if (isinstance(total, int)
                        and not isinstance(total, bool)
                        and sums[k] != total):
                    errors.append(
                        f"per-lane {k} sums to {sums[k]} but "
                        f"counters.{k}={total} — the [R] companion "
                        f"plane must cover the run-total latch "
                        f"exactly")
        # incidents = the supervisor's lane-surgery records: each one
        # merges into its per_lane entry as salvage + requeue context
        incs = lb.get("incidents")
        if incs is not None and not isinstance(incs, list):
            errors.append("lanes.incidents must be an array")
            incs = None
        if incs:
            inc_lanes = {d.get("lane") for d in incs
                         if isinstance(d, dict)}
            for i, d in enumerate(per):
                if not (isinstance(d, dict) and d.get("quarantined")
                        and d.get("lane") in inc_lanes):
                    continue
                where = f"lanes.per_lane[{i}]"
                if "salvage" not in d or "requeue" not in d:
                    errors.append(f"{where}: quarantined lane with an "
                                  f"incident must carry its salvage "
                                  f"pointer + requeue context")
                elif not d.get("salvage"):
                    warnings.append(f"{where}: lane surgery ran but "
                                    f"the salvage write failed (lane "
                                    f"requeues without clean-slice "
                                    f"evidence)")
                rq_ = d.get("requeue")
                if isinstance(rq_, dict) and not isinstance(
                        rq_.get("regrow"), dict):
                    errors.append(f"{where}: requeue.regrow must map "
                                  f"trip knobs to grown capacities")
            for q in seen_quar:
                if q not in inc_lanes:
                    warnings.append(
                        f"lane {q} quarantined with no incident "
                        f"record (unsupervised run, or quarantine "
                        f"predates this supervisor chain)")
        elif seen_quar:
            warnings.append(
                f"{len(seen_quar)} lane(s) quarantined with no "
                f"salvage (unsupervised run — nothing extracted)")
        # per-window telemetry fan-out vs the device counter: on a
        # lossless single-chain run the [W,R] ring plane's deltas must
        # sum to each lane's cumulative events_exec
        les = tel.get("lane_events_sum")
        if (isinstance(les, list) and rows_ok
                and tel.get("records_lost", 0) == 0
                and man.get("resume_of") is None
                and not man.get("escalations")):
            got = [d.get("events_exec", 0) for d in per
                   if isinstance(d, dict)]
            if len(les) == len(got) and les != got:
                warnings.append(
                    f"telemetry.lane_events_sum={les} vs per-lane "
                    f"events_exec={got} on a lossless run — the "
                    f"per-window fan-out should cover every executed "
                    f"event")
    # flows block (optional): per-flow latency tracing accounting
    fl = man.get("flows")
    if fl is not None:
        e2, w2 = _lint_flows(fl, man.get("counters"), tel)
        errors += e2
        warnings += w2
    # causality block (optional): causal critical-path accounting
    cz = man.get("causality")
    if cz is not None:
        e2, w2 = _lint_causality(cz, tel, fl)
        errors += e2
        warnings += w2
    # admission block (optional): standalone resident-run lease fold
    adm = man.get("admission")
    if adm is not None:
        e2, w2 = _lint_admission(adm)
        errors += e2
        warnings += w2
    # elastic block (optional): degraded-mesh recovery record
    el = man.get("elastic")
    if el is not None:
        e2, w2 = _lint_elastic(el, man.get("health"))
        errors += e2
        warnings += w2
    # sentinel latch report (optional, inside health): validated even
    # without an elastic block — a sentinel-armed run that never
    # degraded still stamps its check/trip accounting
    sent = (man.get("health") or {}).get("sentinel") \
        if isinstance(man.get("health"), dict) else None
    if sent is not None:
        errors += _lint_health_sentinel(sent)
    # profile block (optional): a pointer to a jax.profiler artifact
    prof = man.get("profile")
    if prof is not None:
        if not isinstance(prof, dict) or not prof.get("dir"):
            errors.append('profile must be an object naming its '
                          '"dir" — a capture nobody can find is no '
                          'capture')
    return errors, warnings


_FLEET_TERMINAL = {"done": "ok", "failed": "failed",
                   "quarantined": "quarantined"}
_FLEET_STATUSES = {"queued", "leased", "running"} | set(_FLEET_TERMINAL)


def lint_fleet_manifest_obj(man) -> tuple[list, list]:
    """(errors, warnings) for a parsed fleet_manifest.json
    (shadow_tpu/fleet/manifest.py schema)."""
    errors: list = []
    warnings: list = []
    if not isinstance(man, dict):
        return (["fleet manifest must be a JSON object"], [])
    if man.get("schema") != "shadow-tpu-fleet-manifest":
        errors.append(f'schema must be "shadow-tpu-fleet-manifest", '
                      f'got {man.get("schema")!r}')
    if not isinstance(man.get("schema_version"), int):
        errors.append("schema_version must be an integer")
    if not isinstance(man.get("policy"), dict):
        errors.append('missing the "policy" block')
    for k in ("preempted", "stalled", "complete"):
        if not isinstance(man.get(k), bool):
            errors.append(f"{k} must be a bool, got {man.get(k)!r}")
    jobs = man.get("jobs")
    if not isinstance(jobs, dict) or not jobs:
        errors.append('"jobs" must be a non-empty object')
        return errors, warnings
    counts: dict = {}
    for jid, j in sorted(jobs.items()):
        where = f"jobs[{jid}]"
        if not isinstance(j, dict):
            errors.append(f"{where}: must be an object")
            continue
        st = j.get("status")
        counts[st] = counts.get(st, 0) + 1
        if st not in _FLEET_STATUSES:
            errors.append(f"{where}: unknown status {st!r}")
            continue
        # attempt accounting: monotone non-decreasing 1-based history,
        # attempts == the high-water mark, one history entry per
        # execution (a requeued continuation repeats the attempt
        # number, it never rewinds it)
        hist = j.get("attempt_history")
        if not isinstance(hist, list) or not all(
                isinstance(a, int) and a >= 1 for a in hist):
            errors.append(f"{where}: attempt_history must be a list "
                          f"of attempt numbers >= 1")
            hist = []
        if any(b < a for a, b in zip(hist, hist[1:])):
            errors.append(f"{where}: attempt_history must be "
                          f"monotone non-decreasing, got {hist}")
        att = j.get("attempts")
        if not isinstance(att, int) or att < 0:
            errors.append(f"{where}: attempts must be a non-negative "
                          f"integer")
        elif hist and att != max(hist):
            errors.append(f"{where}: attempts={att} disagrees with "
                          f"attempt_history high-water {max(hist)}")
        ex = j.get("executions")
        if isinstance(ex, int) and hist and ex != len(hist):
            errors.append(f"{where}: executions={ex} but "
                          f"{len(hist)} attempt_history entries")
        bh = j.get("backoff_history", [])
        if not isinstance(bh, list) or not all(
                isinstance(b, (int, float)) and b >= 0 for b in bh):
            errors.append(f"{where}: backoff_history must hold "
                          f"non-negative delays")
        # terminal jobs carry a verdict; the verdict matches status
        verdict = j.get("verdict")
        want = _FLEET_TERMINAL.get(st)
        if want is not None and verdict != want:
            errors.append(f"{where}: terminal status {st!r} must "
                          f"carry verdict {want!r}, got {verdict!r}")
        if want is None and verdict is not None:
            errors.append(f"{where}: non-terminal job carries a "
                          f"verdict ({verdict!r})")
        if st == "done" and not isinstance(j.get("result"), dict):
            errors.append(f"{where}: done job must carry its result")
        # SLO verdict (optional, tenant jobs): the verdict must be
        # arithmetic over the flow percentiles it rides with
        res = j.get("result")
        if isinstance(res, dict) and res.get("slo") is not None:
            errors += _lint_slo_verdict(res["slo"], j.get("flows"),
                                        f"{where}.result.slo")
        if st == "failed" and not isinstance(j.get("failure"), dict):
            errors.append(f"{where}: failed job must carry its "
                          f"failure report")
        if st == "quarantined":
            if not j.get("quarantine_reason"):
                errors.append(f"{where}: quarantined job must state "
                              f"its reason")
            sal = j.get("salvage")
            if not isinstance(sal, dict) or not sal.get("dir"):
                errors.append(f"{where}: quarantined job must carry "
                              f"salvage pointers (at least the job "
                              f"dir)")
            elif not any(sal.get(k) for k in
                         ("checkpoint", "run_manifest", "result")):
                warnings.append(f"{where}: quarantined with no "
                                f"checkpoint/manifest/result salvaged "
                                f"(died before its first checkpoint?)")
        # packed jobs (replicas > 1) surface per-lane verdicts at the
        # entry level; every quarantined lane's requeue child must be
        # a replicas=1 standalone spec back-linked via lane_of, and
        # the runner backfills it into this same queue
        rep = j.get("replicas")
        if rep is not None and (not isinstance(rep, int)
                                or isinstance(rep, bool) or rep < 2):
            errors.append(f"{where}: replicas must be an integer >= 2 "
                          f"when present, got {rep!r}")
        lanes = j.get("lanes")
        if lanes is not None:
            if not isinstance(lanes, dict):
                errors.append(f"{where}: lanes must be an object")
                lanes = {}
            if rep is None:
                errors.append(f"{where}: lane verdicts on a job that "
                              f"does not declare replicas")
            ql = lanes.get("quarantined")
            if not isinstance(ql, list) or not ql:
                errors.append(f"{where}: lanes block without "
                              f"quarantined lanes (omit the block for "
                              f"all-healthy packed jobs)")
                ql = []
            for ci, child in enumerate(lanes.get("requeues") or []):
                cw = f"{where}.lanes.requeues[{ci}]"
                if not isinstance(child, dict):
                    errors.append(f"{cw}: must be an object")
                    continue
                if child.get("lane_of") != jid:
                    errors.append(f"{cw}: lane_of="
                                  f"{child.get('lane_of')!r} must "
                                  f"back-link the packed parent "
                                  f"{jid!r}")
                if child.get("replicas", 1) != 1:
                    errors.append(f"{cw}: a lane requeue must be a "
                                  f"replicas=1 standalone spec")
                cid = child.get("id")
                if isinstance(cid, str) and cid not in jobs:
                    warnings.append(f"{cw}: child {cid!r} not (yet) "
                                    f"backfilled into the queue — "
                                    f"fleet killed between fold and "
                                    f"backfill?")
        lof = j.get("lane_of")
        if lof is not None:
            parent = jobs.get(lof)
            if not isinstance(parent, dict):
                errors.append(f"{where}: lane_of names unknown job "
                              f"{lof!r}")
            elif not parent.get("replicas"):
                errors.append(f"{where}: lane_of parent {lof!r} is "
                              f"not a packed job")
        # bucket-affinity fields (fleet/affinity.py): the scheduling
        # key is spec-derived and always present on new manifests; the
        # program key appears once the job's run reported one
        ak = j.get("affinity_key")
        if ak is not None and (not isinstance(ak, str)
                               or not _AFFINITY_KEY.match(ak)):
            errors.append(f'{where}: affinity_key must match "ak" + '
                          f"16 hex chars, got {ak!r}")
        pk = j.get("program_key")
        if pk is not None and (not isinstance(pk, str)
                               or not _PROGRAM_KEY.match(pk)):
            errors.append(f'{where}: program_key must match "pk" + '
                          f"16 hex chars, got {pk!r}")
    # affinity consistency: two jobs the scheduler binned together
    # (equal affinity_keys) must have realized the same compiled
    # program — a divergence means the spec-derived key is lying
    # about program identity
    prog_of_aff: dict = {}
    for jid, j in sorted(jobs.items()):
        if not isinstance(j, dict):
            continue
        ak, pk = j.get("affinity_key"), j.get("program_key")
        if not (isinstance(ak, str) and isinstance(pk, str)):
            continue
        seen = prog_of_aff.setdefault(ak, (jid, pk))
        if seen[1] != pk:
            errors.append(
                f"jobs[{jid}] and jobs[{seen[0]}] share affinity_key "
                f"{ak} but realized different program_keys "
                f"({pk} vs {seen[1]}) — the affinity key must be a "
                f"program-identity invariant")
    # flows roll-up (optional): the fleet-level totals must equal the
    # sums over the per-job flow summaries — the roll-up is derived,
    # so a divergence means the manifest writer and the job results
    # went out of sync
    ft = man.get("flows")
    job_fl = {jid: j["flows"] for jid, j in sorted(jobs.items())
              if isinstance(j, dict) and isinstance(j.get("flows"),
                                                    dict)}
    for jid, fl in job_fl.items():
        where = f"jobs[{jid}].flows"
        cnt = {}
        for k in ("sampled", "recorded", "harvested", "lost_ring",
                  "lost_window_clamp"):
            v = fl.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{where}.{k} must be a non-negative "
                              f"integer, got {v!r}")
            else:
                cnt[k] = v
        if len(cnt) == 5 and cnt["recorded"] + cnt["lost_window_clamp"] \
                != cnt["sampled"]:
            errors.append(
                f"{where}: recorded={cnt['recorded']} + "
                f"lost_window_clamp={cnt['lost_window_clamp']} != "
                f"sampled={cnt['sampled']}")
    if ft is not None:
        if not isinstance(ft, dict):
            errors.append('"flows" must be an object')
        elif not job_fl:
            errors.append('fleet "flows" roll-up with no flow-traced '
                          'job entries')
        else:
            if ft.get("jobs") != len(job_fl):
                errors.append(f"flows.jobs={ft.get('jobs')!r} but "
                              f"{len(job_fl)} job(s) carry a flows "
                              f"summary")
            for k in ("sampled", "recorded", "harvested", "lost_ring",
                      "lost_window_clamp"):
                want = sum(int(fl.get(k, 0) or 0)
                           for fl in job_fl.values())
                if ft.get(k) != want:
                    errors.append(f"flows.{k}={ft.get(k)!r} but the "
                                  f"job summaries sum to {want}")
            want_lanes: dict = {}
            for fl in job_fl.values():
                for lane, summ in (fl.get("per_lane") or {}).items():
                    if isinstance(summ, dict):
                        want_lanes[lane] = (want_lanes.get(lane, 0)
                                            + int(summ.get("count", 0)
                                                  or 0))
            if ft.get("lane_samples") != want_lanes:
                errors.append(f"flows.lane_samples="
                              f"{ft.get('lane_samples')!r} but the "
                              f"job per-lane counts sum to "
                              f"{want_lanes}")
    elif job_fl:
        errors.append(f'{len(job_fl)} job(s) carry flow summaries but '
                      f'the fleet manifest has no "flows" roll-up')
    # causality roll-up (optional): same derived-totals rule — the
    # fleet block must be the exact fold of the per-job causality
    # summaries, including the binding-cause histogram
    ct = man.get("causality")
    job_cz = {jid: j["causality"] for jid, j in sorted(jobs.items())
              if isinstance(j, dict)
              and isinstance(j.get("causality"), dict)}
    for jid, cz in job_cz.items():
        where = f"jobs[{jid}].causality"
        for k in ("sampled", "harvested", "lost_ring",
                  "windows_attributed", "windows_lost"):
            v = cz.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"{where}.{k} must be a non-negative "
                              f"integer, got {v!r}")
        if isinstance(cz.get("harvested"), int) \
                and isinstance(cz.get("lost_ring"), int) \
                and isinstance(cz.get("sampled"), int) \
                and cz["harvested"] + cz["lost_ring"] > cz["sampled"]:
            errors.append(
                f"{where}: harvested={cz['harvested']} + lost_ring="
                f"{cz['lost_ring']} exceeds sampled={cz['sampled']}")
        for name in (cz.get("causes") or {}):
            if name not in _CAUSE_NAMES:
                errors.append(f"{where}.causes[{name!r}]: unknown "
                              f"binding cause")
    if ct is not None:
        if not isinstance(ct, dict):
            errors.append('"causality" must be an object')
        elif not job_cz:
            errors.append('fleet "causality" roll-up with no '
                          'causality-traced job entries')
        else:
            if ct.get("jobs") != len(job_cz):
                errors.append(f"causality.jobs={ct.get('jobs')!r} but "
                              f"{len(job_cz)} job(s) carry a "
                              f"causality summary")
            for k in ("sampled", "harvested", "lost_ring",
                      "windows_attributed", "windows_lost"):
                want = sum(int(cz.get(k, 0) or 0)
                           for cz in job_cz.values())
                if ct.get(k) != want:
                    errors.append(f"causality.{k}={ct.get(k)!r} but "
                                  f"the job summaries sum to {want}")
            want_causes: dict = {}
            for cz in job_cz.values():
                for name, n in (cz.get("causes") or {}).items():
                    want_causes[name] = (want_causes.get(name, 0)
                                         + int(n or 0))
            if ct.get("causes") != want_causes:
                errors.append(f"causality.causes="
                              f"{ct.get('causes')!r} but the job "
                              f"histograms fold to {want_causes}")
    elif job_cz:
        errors.append(f'{len(job_cz)} job(s) carry causality '
                      f'summaries but the fleet manifest has no '
                      f'"causality" roll-up')
    # elastic roll-up (optional): same derived-totals rule — the
    # fleet block must be the exact fold of the per-job elastic
    # records and device-loss requeue counts
    et = man.get("elastic")
    job_el = {jid: j for jid, j in sorted(jobs.items())
              if isinstance(j, dict)
              and (isinstance(j.get("elastic"), dict)
                   or int(j.get("device_losses", 0) or 0) > 0)}
    for jid, j in sorted(jobs.items()):
        if not isinstance(j, dict):
            continue
        dl = j.get("device_losses", 0)
        if not isinstance(dl, int) or isinstance(dl, bool) or dl < 0:
            errors.append(f"jobs[{jid}].device_losses must be a "
                          f"non-negative integer, got {dl!r}")
        so = j.get("shards_override")
        if so is not None and not _is_pow2(so):
            errors.append(f"jobs[{jid}].shards_override must be a "
                          f"positive power of two, got {so!r}")
        jel = j.get("elastic")
        if jel is not None:
            # per-job structural checks; health lives in the job's
            # run_manifest, not here, so sentinel cross-checks are
            # skipped (health=None)
            e2, w2 = _lint_elastic(jel, None)
            errors += [f"jobs[{jid}].{m}" for m in e2]
            warnings += [f"jobs[{jid}].{m}" for m in w2]
    if et is not None:
        if not isinstance(et, dict):
            errors.append('"elastic" must be an object')
        elif not job_el:
            errors.append('fleet "elastic" roll-up with no elastic '
                          'job entries')
        else:
            if et.get("jobs") != len(job_el):
                errors.append(f"elastic.jobs={et.get('jobs')!r} but "
                              f"{len(job_el)} job(s) carry an elastic "
                              f"record or device losses")
            want = {"device_lost": 0, "shard_divergence": 0,
                    "mesh_shrinks": 0, "ladder_steps": 0,
                    "fleet_requeues": 0}
            for j in job_el.values():
                want["fleet_requeues"] += int(
                    j.get("device_losses", 0) or 0)
                jel = j.get("elastic")
                if isinstance(jel, dict):
                    want["device_lost"] += len(jel.get("losses") or ())
                    want["shard_divergence"] += len(
                        jel.get("divergences") or ())
                    want["mesh_shrinks"] += len(
                        jel.get("mesh_transitions") or ())
                    want["ladder_steps"] += len(
                        jel.get("ladder_steps") or ())
            for k, v in want.items():
                if et.get(k) != v:
                    errors.append(f"elastic.{k}={et.get(k)!r} but the "
                                  f"job records fold to {v}")
    elif job_el:
        errors.append(f'{len(job_el)} job(s) carry elastic records '
                      f'but the fleet manifest has no "elastic" '
                      f'roll-up')
    # admission block (optional): a resident program's lease-table
    # roll-up (fleet/admission.py manifest_block)
    adm = man.get("admission")
    if adm is not None:
        e2, w2 = _lint_admission(adm)
        errors += e2
        warnings += w2
    # sweep block (optional): this fleet is one sweep's execution
    # substrate (sweep/driver.py sweep_block) — lattice conservation,
    # ranking re-derivation, census vs prewarm log
    sw = man.get("sweep")
    if sw is not None:
        e2, w2 = _lint_sweep(sw, jobs)
        errors += e2
        warnings += w2
    mc = man.get("counts")
    if isinstance(mc, dict) and mc != counts:
        errors.append(f"counts block {mc} disagrees with the jobs "
                      f"({counts})")
    if man.get("complete"):
        stuck = sorted(jid for jid, j in jobs.items()
                       if isinstance(j, dict)
                       and j.get("status") not in _FLEET_TERMINAL)
        if stuck:
            errors.append(f"manifest claims complete but jobs are "
                          f"non-terminal: {stuck}")
    q = counts.get("quarantined", 0)
    if q:
        warnings.append(f"{q} job(s) quarantined (parked with "
                        f"salvage; see jobs[*].salvage)")
    return errors, warnings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="validate telemetry exports (Chrome-trace JSON "
                    "and/or run manifest)")
    ap.add_argument("--trace", default=None, help="trace JSON path")
    ap.add_argument("--manifest", default=None,
                    help="run_manifest.json path")
    ap.add_argument("--fleet-manifest", default=None,
                    help="fleet_manifest.json path (shadow_tpu.fleet)")
    ap.add_argument("--salvage", default=None,
                    help="lane-salvage .npz path (lease eviction / "
                         "quarantine artifact)")
    ap.add_argument("--checkpoint", default=None,
                    help="snapshot .npz path — validate the "
                         "verified-state ledger stamp (elastic meta)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress warnings, print errors only")
    args = ap.parse_args(argv)
    if not (args.trace or args.manifest or args.fleet_manifest
            or args.salvage or args.checkpoint):
        ap.error("give --trace, --manifest, --fleet-manifest, "
                 "--salvage and/or --checkpoint")

    errors: list = []
    warnings: list = []
    for path, lint in ((args.trace, lint_trace_obj),
                       (args.manifest, lint_manifest_obj),
                       (args.fleet_manifest, lint_fleet_manifest_obj)):
        if not path:
            continue
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: {e}")
            continue
        e2, w2 = lint(obj)
        errors += [f"{path}: {m}" for m in e2]
        warnings += [f"{path}: {m}" for m in w2]
    if args.salvage:
        errors += lint_salvage(args.salvage)
    if args.checkpoint:
        e2, w2 = lint_checkpoint_elastic(args.checkpoint)
        errors += e2
        warnings += w2

    for e in errors:
        print(f"ERROR: {e}", file=sys.stderr)
    if not args.quiet:
        for w in warnings:
            print(f"WARNING: {w}", file=sys.stderr)
    if errors:
        print(f"{len(errors)} error(s), {len(warnings)} warning(s)",
              file=sys.stderr)
        return 1
    print(f"OK ({len(warnings)} warning(s))", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
