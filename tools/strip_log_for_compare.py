#!/usr/bin/env python3
"""Canonicalize shadow-tpu logs for determinism diffing — the analog
of the reference's src/tools/strip_log_for_compare.py: strip the
parts of a log that legitimately differ between repeated identical
experiments (wall-clock timings, memory-address-like tokens, rate
fields), so two runs can be byte-compared (the reference's
determinism gate, determinism1_compare.cmake).

What is stripped:
- `wall_seconds` / `events_per_second` /
  `simulated_seconds_per_wall_second` values inside the completion
  JSON (wall-time dependent);
- any 0x-prefixed token (address-like);
- trailing whitespace.

Everything else — sim timestamps, hosts, heartbeat counters, event
counts — is part of the determinism contract and is kept.

Usage: strip_log_for_compare.py logfile outputfile
"""

from __future__ import annotations

import re
import sys

WALL_RE = re.compile(
    r'"(wall_seconds|events_per_second|simulated_seconds_per_wall_second)"'
    r":\s*[0-9.eE+-]+")
ADDR_RE = re.compile(r"\b0x[0-9a-fA-F]+\b")


def strip_line(line: str) -> str:
    line = WALL_RE.sub(r'"\1": X', line)
    line = ADDR_RE.sub("0xX", line)
    return line.rstrip() + "\n"


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) < 2:
        print(f"USAGE: {sys.argv[0]} logfile outputfile",
              file=sys.stderr)
        return 1
    n = 0
    with open(argv[0]) as inf, open(argv[1], "w") as outf:
        for line in inf:
            outf.write(strip_line(line))
            n += 1
    print(f"Done! Processed {n} lines.", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
