import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from shadow_tpu.core import simtime
from shadow_tpu.net.build import HostSpec, build
from shadow_tpu.net.state import NetConfig, SocketType
from shadow_tpu.process import vproc
from shadow_tpu.process.vproc import ProcessRuntime

GRAPH = open("tests/test_vproc.py").read().split('GRAPH = """')[1].split('"""')[0]
cfg = NetConfig(num_hosts=2, end_time=20 * simtime.ONE_SECOND)
hosts = [HostSpec(name="client", ip="11.0.0.1"),
         HostSpec(name="server", ip="11.0.0.2")]
b = build(cfg, GRAPH, hosts)
server_ip = b.ip_of("server")
log = []
PORT = 7000

def server(host):
    fd = yield vproc.socket(SocketType.UDP)
    yield vproc.bind(fd, PORT)
    for _ in range(3):
        src_ip, src_port, n = yield vproc.recvfrom(fd)
        t = yield vproc.gettime()
        print(f"  server got {n}B at {t/1e6:.3f}ms")
        yield vproc.sendto(fd, src_ip, src_port, n)
    yield vproc.close(fd)

def client(host):
    fd = yield vproc.socket(SocketType.UDP)
    yield vproc.bind(fd, 0)
    for i in range(3):
        t0 = yield vproc.gettime()
        yield vproc.sendto(fd, server_ip, PORT, 100)
        src, sport, n = yield vproc.recvfrom(fd)
        t1 = yield vproc.gettime()
        print(f"  client rtt {i}: {(t1-t0)/1e6:.3f}ms  t0={t0/1e6:.3f} t1={t1/1e6:.3f}")
        log.append((n, t1 - t0))
    yield vproc.close(fd)

rt = ProcessRuntime(b)
rt.spawn(b.host_of("server"), server)
rt.spawn(b.host_of("client"), client, start_time=simtime.ONE_SECOND)

orig = rt._jit_window
def traced(sim, wstart, wend):
    print(f"window [{int(wstart)/1e6:.3f}, {int(wend)/1e6:.3f}) ms")
    return orig(sim, wstart, wend)
rt._jit_window = traced
sim, stats = rt.run()
print("log:", [(n, r/1e6) for n, r in log])
