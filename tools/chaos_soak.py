#!/usr/bin/env python3
"""Randomized chaos soak for the self-healing supervisor.

Each trial assembles a hostile-but-lawful run from a seeded RNG:

- a random fault plan over loss / latency / linkdown / linkup (crash
  and restart are excluded on purpose — a crash flushes a host's event
  row non-conservatively, which would void the exact ledger the soak
  asserts; see faults/conserve.py),
- a deliberately undersized event queue, so the overflow latch trips
  and the supervisor must escalate (grow + rebuild + transplant)
  rather than retry,
- a random number of simulated preemption kills: the stop flag fires
  at a random round barrier, the supervisor takes its final snapshot
  and raises Preempted, and the trial resumes the chain from that
  snapshot — exactly the SIGTERM/--resume path minus the signal.

The oracle is the per-window conservation ledger (faults/conserve.py):
at every round barrier of every attempt of every segment,
pushed == processed + queued + outboxed (exact, since healed runs
carry zero overflow), and window starts / counters stay monotone.
Samples from windows that a resume replays are superseded by the
replay (the checkpoint contract makes them bit-identical), mirroring
conserve.stitch.

With --verify each trial also re-runs the whole simulation
uninterrupted at the final (post-escalation) capacities and demands
the final device state be bit-identical to the healed chain's — the
acceptance check for "escalation reproduces the from-scratch run at
grown capacity".

Usage:
  chaos_soak.py --trials 20 --seed 1 [--kills 2] [--verify]
  chaos_soak.py --trials 5 --replicas 4     # blast-radius mode
One JSON line per trial on stdout; exit 1 if any trial fails.
--replicas R packs R PHOLD lanes into one program (core/lanes.py),
floods one seeded victim lane's event rows mid-run, and asserts the
victim quarantines while every neighbor lane's final per-host state
stays byte-identical to a clean packed run — the containment oracle
for lane-isolated health latches.
--sweep runs one small halving sweep (sweep/driver.py) clean and
again under one SIGKILL per fleet round, asserting lattice
conservation, quarantine accounting, and byte-identical rankings.
--device-loss runs sharded trials killing one victim shard on two
consecutive dispatches (poisoned dispatch_wrap), asserting the
supervisor walks retry -> shrink-to-survivors, the healed run is
byte-identical to an uninterrupted full-width control, and the
elastic block + checkpoint ledger stamps are lint-clean
(parallel/elastic.py).
tests/test_escalate.py imports run_trial() for the fixed-seed tier-1
smoke; the multi-trial soak is the `slow`-marked variant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

GRAPH = """<graphml xmlns="http://graphml.graphdrawing.org/xmlns">
  <key attr.name="latency" attr.type="double" for="edge" id="lat" />
  <key attr.name="bandwidthup" attr.type="int" for="node" id="up" />
  <key attr.name="bandwidthdown" attr.type="int" for="node" id="dn" />
  <graph edgedefault="undirected">
    <node id="v0"><data key="up">102400</data><data key="dn">102400</data>
    </node>
    <edge source="v0" target="v0"><data key="lat">50.0</data></edge>
  </graph>
</graphml>"""


def random_records(rng: np.random.Generator, *, sim_s: int):
    """A lawful random plan over the conservation-safe kinds. Link
    flaps are generated as down/up pairs so the topology never stays
    dark to the end (an all-dark run finishes early and legally, but
    soaks nothing)."""
    from shadow_tpu.core import simtime
    from shadow_tpu.faults.plan import (FaultKind, FaultRecord,
                                        validate_records)

    SEC = simtime.ONE_SECOND
    end = sim_s * SEC
    recs = []
    for _ in range(int(rng.integers(2, 6))):
        t = int(rng.integers(SEC // 10, end - SEC // 10))
        roll = rng.random()
        if roll < 0.45:
            recs.append(FaultRecord(
                t_ns=t, kind=FaultKind.LOSS, a=0, b=0,
                value=int(rng.integers(50_000, 300_000))))
        elif roll < 0.8:
            recs.append(FaultRecord(
                t_ns=t, kind=FaultKind.LATENCY, a=0, b=0,
                value=int(rng.integers(100_000, 5_000_000))))
        else:
            up = min(t + int(rng.integers(50, 200)) * 1_000_000, end - 1)
            recs.append(FaultRecord(t_ns=t, kind=FaultKind.LINK_DOWN,
                                    a=0, b=0))
            recs.append(FaultRecord(t_ns=up, kind=FaultKind.LINK_UP,
                                    a=0, b=0))
    recs.sort(key=lambda r: r.t_ns)
    errors, _ = validate_records(recs, num_vertices=1)
    assert not errors, errors  # generator bug, not a sim bug
    return recs


def _build(hosts, load, sim_s, seed, caps):
    from shadow_tpu.apps import phold
    from shadow_tpu.core import simtime
    from shadow_tpu.net.build import HostSpec, build
    from shadow_tpu.net.state import NetConfig

    cfg = NetConfig(num_hosts=hosts, tcp=False,
                    end_time=sim_s * simtime.ONE_SECOND, seed=seed,
                    event_capacity=caps["event_capacity"],
                    outbox_capacity=caps["outbox_capacity"],
                    router_ring=caps["router_ring"],
                    in_ring=max(8, 2 * load))
    specs = [HostSpec(name=f"p{i}", proc_start_time=0)
             for i in range(hosts)]
    b = build(cfg, GRAPH, specs)
    b.sim = phold.setup(b.sim, load=load)
    return b


def run_trial(seed: int, *, hosts: int = 8, load: int = 2,
              sim_s: int = 1, kills: int = 2,
              undersize: bool = True, max_grow: int = 8,
              checkpoint_every: int = 4, workdir: str | None = None,
              verify: bool = False, log=None) -> dict:
    """One healed run: random plan + undersized capacity + `kills`
    random preemption kills, conservation-checked at every barrier.
    Returns a JSON-able report; report["ok"] is the verdict."""
    from shadow_tpu import faults
    from shadow_tpu.apps import phold
    from shadow_tpu.faults import conserve

    rng = np.random.default_rng(seed)
    records = random_records(rng, sim_s=sim_s)
    roomy = max(32, 4 * load)
    caps = {"event_capacity": (int(rng.integers(1, load + 1))
                               if undersize else roomy),
            "outbox_capacity": roomy,
            "router_ring": roomy}
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="chaos_soak.")

    def make_bundle():
        b = _build(hosts, load, sim_s, seed, caps)
        faults.install(b, records)
        return b

    def rebuild(overrides):
        caps.update(overrides)  # next segment boots at grown shapes too
        return make_bundle()

    # The conservation ledger, sampled at every round barrier. A
    # resume replays from its snapshot, so a non-advancing wstart
    # supersedes the stale tail (conserve.stitch semantics, applied
    # online); cumulative processed restarts from the last kept
    # barrier — the snapshot the replay resumed from.
    samples: list = []

    def on_round(sim, wstats, wstart, wend, next_min):
        while samples and samples[-1].wstart >= wstart:
            samples.pop()
        base = samples[-1].processed if samples else 0
        delta = int(np.asarray(wstats.events_processed))
        samples.append(conserve.sample(
            sim, wstart=wstart, wend=wend, next_min=next_min,
            processed_total=base + delta))
        ctl["rounds"] += 1

    ctl = {"rounds": 0, "kill_at": None}

    def stop():
        return (ctl["kill_at"] is not None
                and ctl["rounds"] >= ctl["kill_at"])

    kills_left = kills
    segments = 0
    escalation_restarts = 0
    retries_used = 0
    resume_from = None
    result = None
    while True:
        segments += 1
        ctl["rounds"] = 0
        ctl["kill_at"] = (int(rng.integers(2, 12))
                          if kills_left > 0 else None)
        res = faults.run_supervised(
            make_bundle(), app_handlers=(phold.handler,),
            checkpoint_path=os.path.join(workdir, "ck"),
            checkpoint_every_windows=checkpoint_every,
            max_retries=2, sleep=lambda s: None,
            escalation=faults.EscalationPolicy(max_grow=max_grow),
            rebuild=rebuild, stop=stop, resume_from=resume_from,
            on_round=on_round, log=log,
            # deterministic run ids (instead of uuids) make the whole
            # report reproducible byte for byte — the fleet-vs-serial
            # identity check depends on it
            run_id=f"s{seed}.g{segments}")
        escalation_restarts += res.escalation_restarts
        retries_used += res.retries_used
        if res.preempted:
            kills_left -= 1
            resume_from = res.final_checkpoint
            continue
        result = res
        break

    errors = conserve.check(samples)
    if result.ok:
        final = conserve.sample(
            result.sim, wstart=0, wend=1, next_min=1,
            processed_total=0)
        if final.drops:
            errors.append(f"healed run ended with drops={final.drops} "
                          f"— overflow latch survived escalation")
    else:
        errors.append("chain did not finish ok: "
                      + json.dumps(result.failure_report()))

    verified = None
    if verify and result.ok:
        verified = _verify_final(result.sim, make_bundle, errors)

    report = {
        "seed": int(seed),
        "ok": bool(result.ok and not errors),
        "segments": segments,
        "kills": kills - kills_left,
        "escalations": [e.as_dict() for e in result.escalations],
        "escalation_restarts": escalation_restarts,
        "retries_used": retries_used,
        "final_capacities": dict(caps),
        "windows_sampled": len(samples),
        "events": (int(result.stats.events_processed)
                   if result.stats is not None else None),
        "conservation_errors": errors,
        "run_id": result.run_id,
        "resume_of": result.resume_of,
    }
    if verified is not None:
        report["verified_bit_identical"] = verified
    return report


def _verify_final(sim_healed, make_bundle, errors) -> bool:
    """Re-run uninterrupted at the final capacities; the healed
    chain's final state must match bit for bit (the escalation
    acceptance criterion). make_bundle() already builds at the grown
    caps — escalation mutated the shared dict."""
    import jax

    from shadow_tpu.apps import phold
    from shadow_tpu.utils import checkpoint

    sim_ref, _, _ = checkpoint.run_windows(
        make_bundle(), app_handlers=(phold.handler,))
    fa = jax.tree_util.tree_flatten_with_path(sim_healed)[0]
    fb = jax.tree_util.tree_flatten_with_path(sim_ref)[0]
    same = True
    for (pa, la), (_, lb) in zip(fa, fb):
        if not np.array_equal(np.asarray(la), np.asarray(lb)):
            errors.append("healed final state diverges from the "
                          "from-scratch run at grown capacity: leaf "
                          + jax.tree_util.keystr(pa))
            same = False
    return same


def _ensure_host_devices(n: int) -> int:
    """Give this process `n` host-platform devices (the sharded soak
    needs a mesh to shrink). Must run BEFORE jax initializes — the
    flag is read once at backend creation; a too-late call just
    reports whatever device count the live backend has."""
    if "jax" not in sys.modules:
        cur = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in cur:
            os.environ["XLA_FLAGS"] = (
                cur + f" --xla_force_host_platform_device_count={n}"
            ).strip()
    import jax

    return len(jax.devices())


def run_device_loss_trial(seed: int, *, shards: int = 8,
                          hosts: int = 8, load: int = 2,
                          sim_s: int = 1, checkpoint_every: int = 2,
                          workdir: str | None = None,
                          log=None) -> dict:
    """Shrink-to-survivors oracle (parallel/elastic.py). One trial:

    1. run the sharded scenario uninterrupted at the full mesh width
       (the control), sentinel attached so every checkpoint carries
       the verified-state ledger stamp;
    2. run it again with a poisoned dispatch killing a seeded victim
       shard on two consecutive dispatches — the first DEVICE_LOST
       steps the ladder's same-mesh retry, the second forces the
       shrink to the pow2-down survivor mesh, resuming from the last
       verified checkpoint via a digest-checked replan;
    3. assert the healed run finishes ok at the shrunk width, its
       elastic block and final checkpoint stamp are lint-clean
       (tools/telemetry_lint.py), the sentinel stayed untripped, and
       the final state is byte-identical to the control's (modulo the
       exchange-tier occupancy telemetry, which legitimately tracks
       mesh width, and the sentinel's barrier counter, which counts
       the resume replay)."""
    import jax
    from jax.sharding import Mesh

    from shadow_tpu import faults
    from shadow_tpu.apps import phold
    from shadow_tpu.parallel import elastic as elastic_mod

    rng = np.random.default_rng(seed)
    devs = jax.devices()
    if len(devs) < shards:
        return {"seed": int(seed), "ok": False, "device_loss_errors": [
            f"need {shards} devices, have {len(devs)} — set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={shards} before "
            f"jax initializes"]}
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="chaos_devloss.")
    roomy = max(32, 4 * load)
    caps = {"event_capacity": roomy, "outbox_capacity": roomy,
            "router_ring": roomy}

    def make_bundle():
        b = _build(hosts, load, sim_s, seed, caps)
        b.sim = elastic_mod.attach_sentinel(b.sim)
        return b

    mesh = Mesh(np.array(devs[:shards]), ("hosts",))
    common = dict(app_handlers=(phold.handler,), mesh=mesh,
                  max_retries=2, sleep=lambda s: None, log=log)
    errors: list = []

    ctrl = faults.run_supervised(
        make_bundle(), checkpoint_path=os.path.join(workdir, "ctrl.ck"),
        checkpoint_every_windows=checkpoint_every,
        run_id=f"dl{seed}.ctrl", **common)
    if not ctrl.ok:
        errors.append("control run failed: "
                      + json.dumps(ctrl.failure_report()))

    # two consecutive poisoned dispatches, mid-run by construction
    # (the counter is global across attempts: the retry's first
    # dispatch is the next index, so the pair walks retry -> shrink)
    victim = int(rng.integers(0, shards))
    kill_at = int(rng.integers(1, max(2, ctrl.dispatches - 1)))
    poison = elastic_mod.make_poisoned_dispatch(
        {kill_at, kill_at + 1}, shard=victim)
    res = faults.run_supervised(
        make_bundle(), checkpoint_path=os.path.join(workdir, "ck"),
        checkpoint_every_windows=checkpoint_every,
        elastic=elastic_mod.ElasticPolicy(),
        dispatch_wrap=poison,
        run_id=f"dl{seed}.chaos", **common)
    el = res.elastic
    if not res.ok:
        errors.append("healed run failed: "
                      + json.dumps(res.failure_report()))
    if el is None:
        errors.append("healed run carries no elastic block")
    else:
        if len(el["losses"]) != 2:
            errors.append(f"expected 2 recorded device losses, got "
                          f"{len(el['losses'])}")
        acts = [s["action"] for s in el["ladder_steps"]]
        if acts != ["retry", "shrink"]:
            errors.append(f"ladder walked {acts}, expected "
                          f"['retry', 'shrink']")
        if el["final_shards"] != shards // 2:
            errors.append(f"final mesh is {el['final_shards']} "
                          f"shard(s), expected {shards // 2} "
                          f"(pow2-down survivors of {shards})")
        lint = _load_lint()
        sent = elastic_mod.sentinel_report(res.sim)
        lerr, _ = lint._lint_elastic(el, {"sentinel": sent})
        if lerr:
            errors.append(f"elastic block not lint-clean: {lerr[:3]}")
        if sent and sent["trips"]:
            errors.append(f"sentinel tripped during a pure device-"
                          f"loss trial: {sent}")
        if res.checkpoints:
            cerr, _ = lint.lint_checkpoint_elastic(
                res.checkpoints[-1][0])
            if cerr:
                errors.append(f"final checkpoint stamp not "
                              f"lint-clean: {cerr[:3]}")
        else:
            errors.append("healed run saved no checkpoints — the "
                          "shrink resumed from nothing")

    # the digest oracle: healed final state == uninterrupted control
    diverged = []
    if ctrl.ok and res.ok:
        skip = {".outbox.max_occupied", ".outbox.narrow_hit",
                ".outbox.narrow_miss"}
        fa = jax.tree_util.tree_flatten_with_path(res.sim)[0]
        fb = jax.tree_util.tree_flatten_with_path(ctrl.sim)[0]
        for (pa, la), (_, lb) in zip(fa, fb):
            key = jax.tree_util.keystr(pa)
            if key in skip or key.startswith(".sentinel"):
                continue
            if not np.array_equal(np.asarray(la), np.asarray(lb)):
                diverged.append(key)
        if diverged:
            errors.append(f"healed state diverges from the "
                          f"uninterrupted control at {diverged[:5]} — "
                          f"shrink-resume is not bit-exact")
        sa = elastic_mod.sentinel_report(res.sim)
        sb = elastic_mod.sentinel_report(ctrl.sim)
        if sa and sb and sa["verified_through_ns"] \
                != sb["verified_through_ns"]:
            errors.append(
                f"verified frontier diverged: healed "
                f"{sa['verified_through_ns']} vs control "
                f"{sb['verified_through_ns']}")

    return {
        "seed": int(seed),
        "ok": not errors,
        "shards": int(shards),
        "victim": victim,
        "kill_at_dispatch": kill_at,
        "control_dispatches": ctrl.dispatches,
        "final_shards": (el or {}).get("final_shards"),
        "ladder": [s["action"] for s in (el or {}).get(
            "ladder_steps", [])],
        "losses": len((el or {}).get("losses", [])),
        "verified_through_ns": (elastic_mod.sentinel_report(res.sim)
                                or {}).get("verified_through_ns")
        if res.sim is not None else None,
        "device_loss_errors": errors,
    }


def _build_packed(replicas, hosts, load, sim_s, seed, caps):
    """R lane copies of the PHOLD scenario in one program: contiguous
    lane blocks (apps/phold.py replica_size) with lane-isolated health
    latches attached."""
    from shadow_tpu.apps import phold
    from shadow_tpu.core import lanes as lanes_mod
    from shadow_tpu.core import simtime
    from shadow_tpu.net.build import HostSpec, build
    from shadow_tpu.net.state import NetConfig

    H = hosts * replicas
    cfg = NetConfig(num_hosts=H, tcp=False,
                    end_time=sim_s * simtime.ONE_SECOND, seed=seed,
                    event_capacity=caps["event_capacity"],
                    outbox_capacity=caps["outbox_capacity"],
                    router_ring=caps["router_ring"],
                    in_ring=max(8, 2 * load))
    specs = [HostSpec(name=f"p{i}", proc_start_time=0)
             for i in range(H)]
    b = build(cfg, GRAPH, specs)
    b.sim = phold.setup(b.sim, load=load, replica_size=hosts)
    b.sim = lanes_mod.attach(b.sim, replicas)
    return b


def _lane_digests(sim, replicas: int) -> list:
    """sha256 per lane over every [H]-leading leaf's lane slice. The
    lane-latch planes, the lease planes, and the telemetry/flow rings
    are excluded (they are the containment mechanism under test, not
    lane state), as are global scalars (the run-total overflow latch
    legitimately differs once the victim lane trips)."""
    import hashlib

    import jax

    H = sim.events.num_hosts
    rs = H // replicas
    hs = [hashlib.sha256() for _ in range(replicas)]
    for path, leaf in jax.tree_util.tree_flatten_with_path(sim)[0]:
        key = jax.tree_util.keystr(path)
        if (".lanes" in key or ".telem" in key or ".admission" in key
                or ".flows" in key or ".inject" in key):
            continue
        a = np.asarray(jax.device_get(leaf))
        if a.ndim == 0 or a.shape[0] != H:
            continue
        for r in range(replicas):
            hs[r].update(key.encode())
            hs[r].update(np.ascontiguousarray(
                a[r * rs:(r + 1) * rs]).tobytes())
    return [h.hexdigest() for h in hs]


def run_replica_trial(seed: int, *, replicas: int = 4, hosts: int = 4,
                      load: int = 2, sim_s: int = 1,
                      log=None) -> dict:
    """Blast-radius containment oracle for packed ensemble runs: run
    the R-lane scenario clean, then again with a seeded flood fault
    overflowing exactly one victim lane's event rows mid-run. The
    victim must quarantine (events_overflow trip, flushed rows), and
    every OTHER lane's final per-host state must be byte-identical to
    the clean run's — a one-lane fault must never perturb a neighbor
    lane."""
    import jax
    import jax.numpy as jnp

    from shadow_tpu.apps import phold
    from shadow_tpu.core import lanes as lanes_mod
    from shadow_tpu.core import simtime
    from shadow_tpu.core.events import push_rows
    from shadow_tpu.net.build import make_runner

    rng = np.random.default_rng(seed)
    victim = int(rng.integers(0, replicas))
    roomy = max(32, 4 * load)
    caps = {"event_capacity": roomy, "outbox_capacity": roomy,
            "router_ring": roomy}
    trig = sim_s * simtime.ONE_SECOND // 2

    b = _build_packed(replicas, hosts, load, sim_s, seed, caps)
    fn = make_runner(b, app_handlers=(phold.handler,),
                     app_bulk=phold.BULK)
    sim_clean, _ = jax.block_until_ready(fn(b.sim))

    cap = int(b.sim.events.capacity)

    def flood_fn(sim, wend):
        Hn = sim.events.num_hosts
        mask = ((jnp.arange(Hn) >= victim * hosts)
                & (jnp.arange(Hn) < (victim + 1) * hosts)
                & (jnp.asarray(wend, simtime.DTYPE) > trig))
        t = jnp.full((Hn,), simtime.INVALID - 1, simtime.DTYPE)
        z = jnp.zeros((Hn,), jnp.int32)
        w = jnp.zeros((Hn, sim.events.words.shape[-1]), jnp.int32)
        q = sim.events
        for _ in range(cap + 1):
            q = push_rows(q, mask, t, z, z, z, w)
        return sim.replace(events=q)

    b2 = _build_packed(replicas, hosts, load, sim_s, seed, caps)
    fn2 = make_runner(b2, app_handlers=(phold.handler,),
                      app_bulk=phold.BULK, fault_fn=flood_fn)
    sim_fault, _ = jax.block_until_ready(fn2(b2.sim))

    errors = []
    rep = lanes_mod.lane_report(sim_fault)
    if not rep[victim]["quarantined"]:
        errors.append(f"victim lane {victim} did not quarantine: "
                      f"{rep[victim]}")
    elif "events_overflow" not in rep[victim].get("trip", []):
        errors.append(f"victim lane {victim} tripped on "
                      f"{rep[victim].get('trip')} instead of the "
                      f"flooded events_overflow latch")
    for r in range(replicas):
        if r != victim and rep[r]["quarantined"]:
            errors.append(f"healthy lane {r} quarantined — the "
                          f"victim's fault leaked: {rep[r]}")
    dig_clean = _lane_digests(sim_clean, replicas)
    dig_fault = _lane_digests(sim_fault, replicas)
    perturbed = [r for r in range(replicas)
                 if r != victim and dig_clean[r] != dig_fault[r]]
    if perturbed:
        errors.append(f"lane(s) {perturbed} diverged from the clean "
                      f"run — one-lane fault perturbed neighbor-lane "
                      f"state (blast radius NOT contained)")
    if log:
        log(f"replica trial seed={seed}: victim={victim} "
            f"trip={rep[victim].get('trip')} errors={len(errors)}")
    return {
        "seed": int(seed),
        "ok": not errors,
        "replicas": int(replicas),
        "victim": victim,
        "victim_trip": rep[victim].get("trip"),
        "victim_flushed": rep[victim].get("flushed"),
        "lane_events_exec": [d["events_exec"] for d in rep],
        "containment_errors": errors,
    }


def _load_lint():
    """Import tools/telemetry_lint.py by path (tools/ is not a
    package; the soak and the lint ship side by side)."""
    import importlib.util

    p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "telemetry_lint.py")
    spec = importlib.util.spec_from_file_location("telemetry_lint", p)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _churn_specs(seed: int):
    """>=4 heterogeneous tenants for one resident program: different
    host counts and loads (all pad to the shared pow2 lane bucket),
    mixed tenant classes. `t-slo` carries an impossible p99 objective
    so the admission gate MUST shed it; the two `t-und*` tenants are
    the undisturbed control group (admitted identically in the
    baseline and churn runs, byte-identity asserted on their terminal
    digests)."""
    from shadow_tpu.fleet.spec import JobSpec

    return [
        JobSpec(id="t-und-prot", kind="scenario", seed=seed + 1,
                hosts=4, load=2, sim_s=1, tenant_class="protected",
                slo_p99_ms=1e9),
        JobSpec(id="t-und-be", kind="scenario", seed=seed + 2,
                hosts=3, load=2, sim_s=1),
        JobSpec(id="t-churn-a", kind="scenario", seed=seed + 3,
                hosts=2, load=1, sim_s=1),
        JobSpec(id="t-slo", kind="scenario", seed=seed + 4,
                hosts=4, load=3, sim_s=1,
                tenant_class="best_effort", slo_p99_ms=1e-6),
        JobSpec(id="t-churn-b", kind="scenario", seed=seed + 5,
                hosts=2, load=2, sim_s=1),
    ]


def run_churn_trial(seed: int, *, lanes: int = 6, horizon_s: int = 4,
                    workdir: str | None = None, log=None) -> dict:
    """Continuous-admission churn oracle (fleet/admission.py).

    One resident program, >=4 heterogeneous tenants, >=8 join/leave/
    evict events, one simulated SIGKILL. Asserts, in order:

    1. zero retraces: the program key is identical before and after
       every admission event and the live trace cache never grows;
    2. SLO shedding: the best-effort tenant breaching its own p99
       objective is evicted within one window barrier of the
       sustained breach, with a lint-clean salvage artifact;
    3. kill/resume: after a SIGKILL (journal abandoned mid-stream
       with a torn tail frame), ResidentProgram.resume reconstructs
       the EXACT resident lease population from replay;
    4. byte-identity: the undisturbed tenants' terminal lane digests
       are identical to a no-churn baseline run's, despite joins,
       leaves, evictions, and the kill in other lanes."""
    from shadow_tpu.core import simtime
    from shadow_tpu.fleet import admission as adm_mod
    from shadow_tpu.fleet import journal as journal_mod

    SEC = simtime.ONE_SECOND
    say = log or (lambda m: None)
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="chaos_churn.")
    specs = _churn_specs(seed)
    errors: list = []

    def _gate():
        return adm_mod.AdmissionGate(sustained=1)

    # --- baseline: the two undisturbed tenants alone, no churn ------
    base = adm_mod.ResidentProgram(
        specs, workdir=os.path.join(workdir, "base"), lanes=lanes,
        horizon_s=horizon_s, gate=_gate(), flow_sample=1, seed=seed,
        fsync=False, log=say)
    for jid in ("t-und-prot", "t-und-be"):
        if base.admit(jid) is None:
            errors.append(f"baseline: {jid} was not admitted")
    base.drain()
    base_digest = {h["job"]: h["digest"] for h in base.table.history
                   if h["state"] == adm_mod.COMPLETED}
    base_key = base.program_key
    base.close()
    for jid in ("t-und-prot", "t-und-be"):
        if jid not in base_digest:
            errors.append(f"baseline: {jid} did not complete: "
                          f"{[h['state'] for h in base.table.history]}")
    if base_key is None:
        errors.append("baseline: program key unavailable (opaque "
                      "loop?) — zero-retrace proof impossible")
    if not base.program_key_stable:
        errors.append("baseline: program key moved without churn")

    # --- churn run: same undisturbed admissions + lane churn --------
    churn_dir = os.path.join(workdir, "churn")
    rp = adm_mod.ResidentProgram(
        specs, workdir=churn_dir, lanes=lanes, horizon_s=horizon_s,
        gate=_gate(), flow_sample=1, seed=seed, fsync=False, log=say)
    for jid in ("t-und-prot", "t-und-be", "t-churn-a", "t-slo"):
        if rp.admit(jid) is None:
            errors.append(f"churn: {jid} was not admitted at t=0")
    rp.advance(until_ns=SEC // 4)
    # the gate must have shed t-slo by now (sustained=1, folds every
    # barrier — "within one window barrier" by construction)
    slo_lease = next((h for h in rp.table.history
                      if h["job"] == "t-slo"), None)
    if slo_lease is None:
        errors.append("churn: t-slo still resident after "
                      f"{rp.dispatches} barriers — the gate never "
                      "shed the SLO-breaching best-effort lane")
    elif slo_lease["state"] != adm_mod.EVICTED:
        errors.append(f"churn: t-slo ended {slo_lease['state']}, "
                      f"expected evicted (reason: "
                      f"{slo_lease.get('reason')})")
    elif "slo breach" not in (slo_lease.get("reason") or ""):
        errors.append(f"churn: t-slo evicted for "
                      f"{slo_lease.get('reason')!r}, not an SLO "
                      f"breach")
    salvage_path = (slo_lease or {}).get("salvage")
    if not salvage_path or not os.path.isfile(salvage_path):
        errors.append(f"churn: t-slo eviction left no salvage "
                      f"artifact ({salvage_path})")
    else:
        lint = _load_lint().lint_salvage(salvage_path)
        if lint:
            errors.append(f"churn: t-slo salvage artifact is not "
                          f"lint-clean: {lint}")
    # operator churn: evict one tenant, admit the other mid-run, then
    # re-admit the evicted one into the shed lane
    if not rp.evict("t-churn-a", reason="operator churn"):
        errors.append("churn: operator evict of t-churn-a failed")
    rp.advance(until_ns=SEC // 2)
    for jid in ("t-churn-b", "t-churn-a"):
        if rp.admit(jid) is None:
            errors.append(f"churn: mid-run admission of {jid} failed")
    if not rp.program_key_stable:
        errors.append(
            f"churn: program retraced before the kill — keys "
            f"{sorted(map(str, rp.program_keys))}, retraces "
            f"{rp.retraces_seen}")

    # --- SIGKILL: abandon the journal mid-stream, torn tail and all -
    expected_pop = {int(k): tuple(v)
                    for k, v in rp.table.population().items()}
    rp.table.journal.close()       # fd gone, no terminal frames: the
    # on-disk journal is exactly what a SIGKILL leaves behind
    lease_log = os.path.join(churn_dir, "leases.log")
    with open(lease_log, "ab") as f:
        # half a frame header: the torn tail a dying writer leaves
        f.write(journal_mod.encode_frame(
            {"ev": "lease", "lane": 0, "state": "free"})[:7])
    del rp

    rp2 = adm_mod.ResidentProgram.resume(
        specs, workdir=churn_dir, lanes=lanes, horizon_s=horizon_s,
        gate=_gate(), flow_sample=1, seed=seed, fsync=False, log=say)
    got_pop = {int(k): tuple(v)
               for k, v in rp2.table.population().items()}
    if got_pop != expected_pop:
        errors.append(f"resume: lease population diverged — expected "
                      f"{expected_pop}, replay gave {got_pop}")
    rp2.drain()
    rp2.close()
    if not rp2.program_key_stable:
        errors.append(
            f"resume: program retraced after the kill — keys "
            f"{sorted(map(str, rp2.program_keys))}, retraces "
            f"{rp2.retraces_seen}")
    keys = {base_key, rp2.program_key}
    if len(keys) != 1:
        errors.append(f"program key differs across runs: {keys}")

    # --- byte-identity of the undisturbed lanes ---------------------
    churn_digest = {h["job"]: h["digest"] for h in rp2.table.history
                    if h["state"] == adm_mod.COMPLETED}
    for jid in ("t-und-prot", "t-und-be"):
        if jid not in churn_digest:
            errors.append(f"churn: undisturbed tenant {jid} did not "
                          f"complete")
        elif churn_digest[jid] != base_digest.get(jid):
            errors.append(
                f"undisturbed tenant {jid} diverged from the "
                f"no-churn baseline ({churn_digest[jid][:12]} != "
                f"{(base_digest.get(jid) or '?')[:12]}) — churn in "
                f"other lanes perturbed a healthy lane")

    # --- event census over the journal ------------------------------
    frames = [r for r in journal_mod.replay(lease_log)[0]
              if r.get("ev") == "lease"]
    joins = sum(1 for r in frames if r["state"] == adm_mod.ADMITTED)
    leaves = sum(1 for r in frames
                 if r["state"] in (adm_mod.COMPLETED,
                                   adm_mod.QUARANTINED))
    evictions = sum(1 for r in frames
                    if r["state"] == adm_mod.EVICTED)
    tenants = {r.get("job") for r in frames if r.get("job")}
    if joins + leaves + evictions < 8:
        errors.append(f"churn schedule too thin: {joins} joins + "
                      f"{leaves} leaves + {evictions} evictions < 8")
    if len(tenants) < 4:
        errors.append(f"churn covered only {len(tenants)} tenants "
                      f"(need >= 4): {sorted(tenants)}")
    lease_warnings = list(rp2.table.fold_warnings)
    return {
        "seed": int(seed),
        "ok": not errors,
        "tenants": len(tenants),
        "joins": joins,
        "leaves": leaves,
        "evictions": evictions,
        "program_key": base_key,
        "program_key_stable": bool(rp2.program_key_stable),
        "population_resumed": {str(k): list(v)
                               for k, v in sorted(got_pop.items())},
        "slo_evicted": (slo_lease or {}).get("job"),
        "salvage": salvage_path,
        "lease_warnings": lease_warnings,
        "churn_errors": errors,
    }


def _sweep_spec(seed: int):
    """A small deterministic halving sweep (2x2 lattice, >= 2 rounds)
    over a simulation-deterministic objective — kills must not be able
    to move the ranking, so the metric must carry no wallclock."""
    from shadow_tpu.sweep import plan as plan_mod

    return plan_mod.SweepSpec.from_obj({
        "sweep": {"id": f"chaos-{seed}",
                  "objective": {"metric": "events", "goal": "max"},
                  "search": {"strategy": "halving", "eta": 2,
                             "budget_field": "sim_s",
                             "budget_scale": 2},
                  "prewarm": False},
        "fleet": {"max_attempts": 3},
        "template": {"kind": "scenario", "hosts": 4, "sim_s": 1,
                     "event_capacity": 24},
        "axes": [{"field": "seed", "values": [seed, seed + 1]},
                 {"field": "load", "values": [1, 2]}],
    })


def run_sweep_trial(seed: int, *, workers: int = 2,
                    workdir: str | None = None, log=None) -> dict:
    """Sweep-under-fire oracle (sweep/driver.py): run one small
    halving sweep clean, then again while SIGKILLing one worker per
    fleet round, and assert

    1. lattice conservation — every expanded point still ends in
       exactly one category, none pending, and the chaos manifest
       (sweep block included) is lint-clean;
    2. quarantine accounting — the sweep block's quarantined count
       equals the manifest's quarantined jobs (divergent points park,
       they never sink the sweep);
    3. ranking identity — every round's ranking, the final table, and
       "best" are byte-identical to the clean run's (deterministic
       objective + the fleet's kill/resume bit-identity contract)."""
    import signal as signal_mod

    from shadow_tpu.fleet import journal as journal_mod
    from shadow_tpu.sweep import driver as sweep_driver

    say = log or (lambda m: None)
    if workdir is None:
        workdir = tempfile.mkdtemp(prefix="chaos_sweep.")
    errors: list = []
    spec = _sweep_spec(seed)

    clean = sweep_driver.SweepDriver(
        os.path.join(workdir, "clean"), spec, workers=workers,
        fsync=False, log=say)
    rc_clean = clean.run()
    clean_block = clean.report()

    killed: list = []

    def on_ev(runner, ev):
        # one SIGKILL per fleet round: the driver builds a fresh
        # runner per round, so a once-per-runner latch is exactly
        # once-per-round; the first job to reach "running" loses its
        # worker mid-execution
        if ev.get("ev") != "running" \
                or getattr(runner, "_chaos_killed", False):
            return
        runner._chaos_killed = True
        pid = runner.worker_pid(ev.get("worker"))
        if pid:
            os.kill(pid, signal_mod.SIGKILL)
            killed.append({"worker": ev.get("worker"),
                           "job": ev.get("job")})
            say(f"sweep chaos: killed {ev.get('worker')} running "
                f"{ev.get('job')}")

    chaos = sweep_driver.SweepDriver(
        os.path.join(workdir, "chaos"), spec, workers=workers,
        fsync=False, on_fleet_event=on_ev, log=say)
    rc_chaos = chaos.run()
    chaos_block = chaos.report()

    if rc_clean != 0:
        errors.append(f"clean sweep exited {rc_clean}")
    if rc_chaos != 0:
        errors.append(f"chaos sweep exited {rc_chaos}")
    if not killed:
        errors.append("no worker was ever killed — the soak soaked "
                      "nothing")
    losses = sum(1 for r in journal_mod.replay(
        os.path.join(workdir, "chaos", "journal.log"))[0]
        if r.get("ev") == "worker_lost")
    if losses < len(killed):
        errors.append(f"{len(killed)} kill(s) but only {losses} "
                      f"worker_lost frame(s) in the fleet journal")

    lint = _load_lint()
    with open(os.path.join(workdir, "chaos",
                           "fleet_manifest.json")) as f:
        man = json.load(f)
    lerr, _ = lint.lint_fleet_manifest_obj(man)
    if lerr:
        errors.append(f"chaos manifest not lint-clean: {lerr[:3]}")
    pts = chaos_block["points"]
    if pts["expanded"] != sum(pts[c] for c in
                              ("completed", "failed", "quarantined",
                               "pruned", "pending")):
        errors.append(f"lattice not conserved under kills: {pts}")
    if pts["pending"]:
        errors.append(f"{pts['pending']} point(s) pending after a "
                      f"complete chaos sweep")
    man_q = sum(1 for j in man["jobs"].values()
                if j.get("status") == "quarantined")
    if pts["quarantined"] > man_q:
        errors.append(f"sweep block claims {pts['quarantined']} "
                      f"quarantined point(s) but the manifest holds "
                      f"{man_q} quarantined job(s)")

    if len(clean_block["rounds"]) != len(chaos_block["rounds"]):
        errors.append(f"round count diverged under kills: "
                      f"{len(clean_block['rounds'])} clean vs "
                      f"{len(chaos_block['rounds'])} chaos")
    for k, (rdc, rdk) in enumerate(zip(clean_block["rounds"],
                                       chaos_block["rounds"])):
        if rdc["ranking"] != rdk["ranking"]:
            errors.append(f"round {k} ranking diverged under kills: "
                          f"{rdc['ranking']} vs {rdk['ranking']}")
    if clean_block["best"] != chaos_block["best"]:
        errors.append(f"best point diverged under kills: "
                      f"{clean_block['best']!r} vs "
                      f"{chaos_block['best']!r}")

    if len(clean_block["rounds"]) < 2:
        errors.append(f"halving produced only "
                      f"{len(clean_block['rounds'])} round(s) — the "
                      f"soak must cross at least one prune")
    return {
        "seed": int(seed),
        "ok": not errors,
        "rounds": len(chaos_block["rounds"]),
        "kills": len(killed),
        "worker_losses": losses,
        "points": pts,
        "best": chaos_block["best"],
        "ranking_identical": (clean_block["ranking"]
                              == chaos_block["ranking"]),
        "sweep_errors": errors,
    }


def _main_fleet(args) -> int:
    """--jobs K: dogfood the fleet runner. Each trial becomes a
    `chaos_trial` job; K worker processes execute them with the full
    durable-queue / lease / requeue machinery, and the reports come
    back through the journal. Output order is seed order (not
    completion order), so the stdout stream is byte-identical to the
    serial path's for the same flags."""
    from shadow_tpu.fleet import FleetPolicy, FleetRunner, JobSpec

    fleet_dir = args.fleet_dir or tempfile.mkdtemp(prefix="chaos_fleet.")
    specs = [JobSpec(id=f"trial-{k:03d}", kind="chaos_trial",
                     seed=args.seed + k, hosts=args.hosts,
                     load=args.load, sim_s=args.sim_s,
                     kills=args.kills, max_grow=args.max_grow,
                     verify=args.verify)
             for k in range(args.trials)]
    runner = FleetRunner(fleet_dir, FleetPolicy(), specs,
                         workers=args.jobs,
                         log=lambda m: print(m, file=sys.stderr))
    rc = runner.run(install_signals=True)
    failed = 0
    for k in range(args.trials):
        j = runner.queue.jobs[f"trial-{k:03d}"]
        rep = (j.result or {}).get("report")
        if rep is None:
            print(json.dumps({"seed": args.seed + k, "ok": False,
                              "fleet_status": j.status,
                              "failure": j.failure}), flush=True)
            failed += 1
        else:
            print(json.dumps(rep), flush=True)
            failed += 0 if rep["ok"] else 1
    print(f"chaos soak: {args.trials - failed}/{args.trials} trials ok "
          f"(fleet: {len(specs)} jobs, exit {rc})", file=sys.stderr)
    return 1 if failed or rc != 0 else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="randomized kill/heal soak over the supervised "
                    "runner (seeded, reproducible)")
    ap.add_argument("--trials", type=int, default=10)
    ap.add_argument("--seed", type=int, default=1,
                    help="base seed; trial k runs at seed+k")
    ap.add_argument("--kills", type=int, default=2,
                    help="preemption kills per trial")
    ap.add_argument("--hosts", type=int, default=8)
    ap.add_argument("--load", type=int, default=2)
    ap.add_argument("--sim-s", type=int, default=1)
    ap.add_argument("--max-grow", type=int, default=8)
    ap.add_argument("--verify", action="store_true",
                    help="also diff each healed run against an "
                         "uninterrupted run at the final capacities")
    ap.add_argument("--jobs", type=int, default=0,
                    help="run the trials through the fleet runner "
                         "(shadow_tpu.fleet) with this many worker "
                         "processes; 0 = serial in-process. Reports "
                         "are byte-identical either way (seeded "
                         "trials, deterministic run ids)")
    ap.add_argument("--fleet-dir", default=None,
                    help="fleet state dir for --jobs (default: a "
                         "fresh temp dir)")
    ap.add_argument("--platform", default=None,
                    help="force a JAX backend (e.g. cpu)")
    ap.add_argument("--replicas", type=int, default=0,
                    help="blast-radius mode: pack this many PHOLD "
                         "lanes per trial, flood one victim lane's "
                         "event rows mid-run, and assert neighbor "
                         "lanes' final state is byte-identical to a "
                         "clean packed run (core/lanes.py "
                         "containment)")
    ap.add_argument("--churn", action="store_true",
                    help="continuous-admission mode: random-free "
                         "join/leave/evict schedule over one resident "
                         "program (fleet/admission.py) with a "
                         "simulated SIGKILL — asserts undisturbed-"
                         "lane byte-identity vs a no-churn run, zero "
                         "retraces across every admission event, SLO "
                         "shedding with a lint-clean salvage, and "
                         "exact lease-population reconstruction on "
                         "resume")
    ap.add_argument("--lanes", type=int, default=6,
                    help="resident lane count for --churn")
    ap.add_argument("--device-loss", action="store_true",
                    help="elastic-recovery mode: sharded trials with "
                         "a poisoned dispatch killing one shard twice "
                         "(retry, then shrink to survivors) — asserts "
                         "the healed run is byte-identical to an "
                         "uninterrupted full-width control and the "
                         "elastic block + checkpoint ledger stamp are "
                         "lint-clean (parallel/elastic.py)")
    ap.add_argument("--shards", type=int, default=8,
                    help="mesh width for --device-loss (forces that "
                         "many host-platform devices)")
    ap.add_argument("--sweep", action="store_true",
                    help="sweep-under-fire mode: run one small "
                         "halving sweep (sweep/driver.py) clean, then "
                         "again killing one worker per round — "
                         "asserts lattice conservation, quarantine "
                         "accounting, and byte-identical rankings")
    ap.add_argument("--workers", type=int, default=2,
                    help="fleet workers per sweep for --sweep")
    args = ap.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.device_loss:
        if args.jobs > 0 or args.replicas > 1 or args.churn \
                or args.sweep:
            ap.error("--device-loss is a standalone elastic soak; it "
                     "does not combine with --jobs/--replicas/--churn/"
                     "--sweep")
        have = _ensure_host_devices(args.shards)
        failed = 0
        for k in range(args.trials):
            rep = run_device_loss_trial(
                args.seed + k, shards=min(args.shards, have),
                hosts=args.hosts, load=args.load, sim_s=args.sim_s)
            print(json.dumps(rep), flush=True)
            if not rep["ok"]:
                failed += 1
        print(f"device-loss soak: {args.trials - failed}/"
              f"{args.trials} trials ok", file=sys.stderr)
        return 1 if failed else 0
    if args.sweep:
        if args.jobs > 0 or args.replicas > 1 or args.churn:
            ap.error("--sweep is a standalone sweep-driver soak; it "
                     "does not combine with --jobs/--replicas/--churn")
        failed = 0
        for k in range(args.trials):
            rep = run_sweep_trial(args.seed + k, workers=args.workers)
            print(json.dumps(rep), flush=True)
            if not rep["ok"]:
                failed += 1
        print(f"sweep soak: {args.trials - failed}/{args.trials} "
              f"trials ok", file=sys.stderr)
        return 1 if failed else 0
    if args.churn:
        if args.jobs > 0 or args.replicas > 1:
            ap.error("--churn is a standalone resident-program soak; "
                     "it does not combine with --jobs or --replicas")
        failed = 0
        for k in range(args.trials):
            rep = run_churn_trial(args.seed + k, lanes=args.lanes)
            print(json.dumps(rep), flush=True)
            if not rep["ok"]:
                failed += 1
        print(f"churn soak: {args.trials - failed}/{args.trials} "
              f"trials ok", file=sys.stderr)
        return 1 if failed else 0
    if args.replicas > 1:
        if args.jobs > 0:
            ap.error("--replicas is a standalone containment soak; "
                     "it does not combine with --jobs")
        failed = 0
        for k in range(args.trials):
            rep = run_replica_trial(
                args.seed + k, replicas=args.replicas,
                hosts=args.hosts, load=args.load, sim_s=args.sim_s)
            print(json.dumps(rep), flush=True)
            if not rep["ok"]:
                failed += 1
        print(f"containment soak: {args.trials - failed}/"
              f"{args.trials} trials ok", file=sys.stderr)
        return 1 if failed else 0
    if args.jobs > 0:
        return _main_fleet(args)

    failed = 0
    for k in range(args.trials):
        rep = run_trial(args.seed + k, hosts=args.hosts, load=args.load,
                        sim_s=args.sim_s, kills=args.kills,
                        max_grow=args.max_grow, verify=args.verify)
        print(json.dumps(rep), flush=True)
        if not rep["ok"]:
            failed += 1
    print(f"chaos soak: {args.trials - failed}/{args.trials} trials ok",
          file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
