"""Op-level device profile of the window loop via jax.profiler (works
through the axon tunnel: the trace.json.gz carries real per-fusion
device durations). Prints the top device ops by total time with their
HLO-metadata source locations when resolvable.

Usage:  python tools/profile_trace.py [--hosts 10240] [--load 8]
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "tpu,cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from tools.perfutil import build_warm_phold


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=10240)
    ap.add_argument("--load", type=int, default=8)
    ap.add_argument("--calls", type=int, default=3)
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args()
    print(f"backend: {jax.default_backend()}")

    w = build_warm_phold(args.hosts, args.load)
    sim, wstart, one_window = w["sim"], w["wstart"], w["one_window"]

    tracedir = tempfile.mkdtemp(prefix="shadowtpu_trace_")
    with jax.profiler.trace(tracedir):
        out = None
        for _ in range(args.calls):
            out = one_window(sim, wstart)
        jax.block_until_ready(out)

    files = glob.glob(os.path.join(tracedir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not files:
        print(f"no trace produced under {tracedir}")
        return
    with gzip.open(files[0]) as f:
        tr = json.load(f)
    ev = tr["traceEvents"] if isinstance(tr, dict) else tr
    pids = {}
    for e in ev:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pids[e["pid"]] = e["args"].get("name", "")
    dur = collections.Counter()
    cnt = collections.Counter()
    for e in ev:
        if e.get("ph") == "X" and "dur" in e:
            pname = pids.get(e["pid"], "")
            if "TPU" in pname or "/device" in pname.lower():
                dur[e["name"]] += e["dur"]
                cnt[e["name"]] += 1
    tot = sum(dur.values())
    print(f"total device op time: {tot / 1e3:.1f} ms over {args.calls} "
          f"calls ({tot / 1e3 / args.calls:.1f} ms/call)")
    for name, d in dur.most_common(args.top):
        print(f"{d / 1e3 / args.calls:9.2f} ms/call  x{cnt[name] // args.calls:4d}  {name[:90]}")
    print(f"trace dir kept at {tracedir}")


if __name__ == "__main__":
    main()
