#!/usr/bin/env python3
"""Scale harness: run PHOLD at BASELINE.json shapes (10k/100k hosts)
and report events/s, device memory, and compile time — the evidence
for the reference's "thousands of nodes on a single machine" claim
(README.md:5-8) and the 100k north star.

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python tools/scale_run.py \
      --hosts 10240 --load 8 --sim-seconds 2 [--cpu]

Prints one JSON line:
  {"hosts", "events", "wall_s", "events_per_sec", "compile_s",
   "device_bytes", "overflow"}
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--hosts", type=int, default=10240)
    ap.add_argument("--load", type=int, default=8)
    ap.add_argument("--sim-seconds", type=int, default=2)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    ap.add_argument("--no-bulk", action="store_true",
                    help="disable the bulk window pass")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import pathlib

    cache = pathlib.Path(__file__).resolve().parent.parent / ".jax_cache"
    jax.config.update("jax_compilation_cache_dir", str(cache))

    import sys

    import numpy as np

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import bench
    from shadow_tpu.apps import phold
    from shadow_tpu.net.build import make_runner

    b = bench._build_phold(args.hosts, args.load, args.sim_seconds,
                           args.seed)
    fn = make_runner(b, app_handlers=(phold.handler,),
                     app_bulk=None if args.no_bulk else phold.BULK)

    t0 = time.perf_counter()
    sim, stats = fn(b.sim)
    jax.block_until_ready(stats.events_processed)
    compile_and_first = time.perf_counter() - t0

    # timed run on a distinct seed (see bench.py on result caching)
    b2 = bench._build_phold(args.hosts, args.load, args.sim_seconds,
                            args.seed + 1)
    jax.block_until_ready(b2.sim.net.rng_keys)
    t0 = time.perf_counter()
    sim, stats = fn(b2.sim)
    ev = int(jax.device_get(stats.events_processed))
    wall = time.perf_counter() - t0

    # ONE resident sim state's device footprint (summing all live
    # arrays would also count the warmup build + inputs, ~3x over)
    dev_bytes = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(sim)
        if hasattr(leaf, "nbytes"))
    ovf = (int(jax.device_get(sim.events.overflow))
           + int(jax.device_get(sim.outbox.overflow))
           + int(jax.device_get(sim.net.rq_overflow)))
    print(json.dumps({
        "hosts": args.hosts,
        "platform": jax.devices()[0].platform,
        "events": ev,
        "wall_s": round(wall, 3),
        "events_per_sec": round(ev / wall, 1),
        "sim_sec_per_wall_sec": round(args.sim_seconds / wall, 3),
        "compile_s": round(compile_and_first - wall, 1),
        "device_bytes": dev_bytes,
        "overflow": ovf,
    }))
    assert int(np.asarray(sim.app.rcvd).sum()) > 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
