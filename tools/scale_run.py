#!/usr/bin/env python3
"""Scale harness: run the BASELINE.json workload shapes at scale and
report events/s, device memory, and compile time — the evidence for
the reference's "thousands of nodes on a single machine" claim
(README.md:5-8) and the 100k north star.

Workloads:
  phold  — PDES scheduler stress (configs #5 shape; default)
  relay  — Tor-relay circuits, 5-hop TCP chains (config #3 shape:
           --hosts 10240 = 2048 concurrent circuits)
  gossip — Bitcoin block flooding over a K-peer graph (config #4
           shape: --hosts 5120)

Usage:
  PYTHONPATH=/root/repo:/root/.axon_site python tools/scale_run.py \
      --workload relay --hosts 10240 --sim-seconds 30 [--cpu]

Prints one JSON line:
  {"hosts", "workload", "events", "wall_s", "events_per_sec",
   "compile_s", "device_bytes", "overflow", "verified"}
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="phold",
                    choices=["phold", "relay", "tor", "gossip"])
    ap.add_argument("--slots", type=int, default=8,
                    help="tor: max circuits one relay/server host "
                         "carries (consensus-weighted draw, capacity "
                         "capped); sockets_per_host = 2 + 2*slots")
    ap.add_argument("--gossip-transport", default="udp",
                    choices=["udp", "tcp"],
                    help="gossip: 'tcp' floods blocks over persistent "
                         "peer connections (the Bitcoin shape, r5); "
                         "'udp' is the original datagram model (and "
                         "the sharded/ensemble one)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="ensemble mode (first-class, VERDICT r4 #7): "
                         "partition --hosts into R independent "
                         "replicas of H/R hosts in ONE device program "
                         "— the seed-sweep shape Shadow users run as "
                         "R processes. Works for every workload: "
                         "phold/gossip use block-diagonal graphs, "
                         "relay/tor confine circuits to their block. "
                         "Reports AGGREGATE events/s")
    ap.add_argument("--hosts", type=int, default=10240)
    ap.add_argument("--load", type=int, default=8)
    ap.add_argument("--hop", type=int, default=5,
                    help="relay circuit length: 5 = the Tor-relay shape "
                         "(config #3), 2 = pairwise client->server bulk "
                         "transfers (config #2's 1k-host tgen shape)")
    ap.add_argument("--bytes", type=int, default=100_000,
                    help="bytes per relay circuit")
    ap.add_argument("--allow-partial", action="store_true",
                    help="report completion fraction instead of "
                         "failing when transfers are unfinished at "
                         "end_time (real-topology RTTs reach ~4.6 s; "
                         "short sims cannot finish slow-start on the "
                         "worst paths — the CPU floor can't afford "
                         "long ones)")
    ap.add_argument("--sim-seconds", type=int, default=2)
    ap.add_argument("--runahead", type=int, default=0,
                    help="minimum window in ms, 0 = the topology's "
                         "honest min path latency. Raising it runs "
                         "fewer, larger windows — the reference's "
                         "--runahead fidelity/throughput trade "
                         "(master.c:133-159): events may execute up to "
                         "this much sim-time later than their causal "
                         "earliest point")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--cap", type=int, default=0,
                    help="event/outbox/router queue capacity override "
                         "(0 = per-workload default). Window cost is "
                         "linear in capacity; overflow is counted, so "
                         "run tight and re-run larger only on a "
                         "nonzero overflow report.")
    ap.add_argument("--chunk", type=int, default=0,
                    help="execute N windows per device call with a "
                         "host outer loop (bit-identical to the "
                         "monolithic program). Long real-topology "
                         "sims NEED this on the tunneled TPU: one "
                         "call covering thousands of windows exceeds "
                         "the backend's per-execution limit "
                         "(UNAVAILABLE). 0 = monolithic")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend")
    ap.add_argument("--no-bulk", action="store_true",
                    help="disable the bulk window pass")
    ap.add_argument("--bulk-lossless", action="store_true",
                    help="compile the narrow loss-free TCP bulk pass: "
                         "loss/retransmit artifacts STOP a host's "
                         "scan (prefix-commit -> serial) instead of "
                         "being modeled. Bit-identical for any "
                         "workload; faster when the workload is "
                         "genuinely artifact-free, slower when it "
                         "is not")
    ap.add_argument("--topology", default="one",
                    choices=["one", "ref"],
                    help="'one' = the single-vertex 50 ms fixture; "
                         "'ref' = the reference's real Internet-derived "
                         "graph (resource/topology.graphml.xml.xz, 183 "
                         "vertices / 16.8k edges) with hosts attached "
                         "by uniform draw — puts the latency gather, "
                         "per-vertex bandwidth diversity, and the "
                         "honest min-jump inside every measured window")
    ap.add_argument("--shards", type=int, default=0,
                    help="run the window loop under shard_map over an "
                         "N-device mesh (0 = single shard). On the CPU "
                         "backend N virtual devices are forced; on TPU "
                         "N must not exceed the real device count")
    args = ap.parse_args()

    if args.bulk_lossless and (
            args.no_bulk or args.workload in ("phold", "gossip")):
        raise SystemExit(
            "--bulk-lossless only applies to the TCP bulk pass "
            "(relay/tor workloads, without --no-bulk)")

    if args.shards > 1:
        import pathlib as _p
        import sys as _s

        _s.path.insert(0, str(_p.Path(__file__).resolve().parent.parent))
        import bench as _b

        _b.force_virtual_devices(args.shards)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    elif os.environ.get("BENCH_ASSUME_DEVICE"):
        # caller already holds a live session (tools/tpu_watch.py runs
        # this in-process under it) — re-probing in a subprocess would
        # start a FRESH backend init, which hangs if the tunnel window
        # has closed even though our held session is fine. The probe
        # path's virtual-CPU-mesh fallback is impossible here: the
        # caller's backend is already initialized, so jax_platforms
        # can no longer be switched — fail loudly instead.
        if args.shards > 1 and len(jax.devices()) < args.shards:
            raise SystemExit(
                f"--shards {args.shards} needs {args.shards} devices "
                f"but the held session has {len(jax.devices())}; run "
                "without BENCH_ASSUME_DEVICE for the virtual-CPU mesh")
    else:
        # shared wedged-tunnel guard (see bench._probe_backend)
        import pathlib as _p
        import sys as _s

        _s.path.insert(0, str(_p.Path(__file__).resolve().parent.parent))
        import bench as _bench

        ndev = _bench._probe_backend()
        if args.shards > 1 and ndev < args.shards:
            # not enough real chips for the mesh: virtual CPU devices
            # (XLA_FLAGS forced above, before the backend initializes)
            jax.config.update("jax_platforms", "cpu")
    import pathlib
    import sys

    import numpy as np

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import bench

    bench.enable_compile_cache()
    from shadow_tpu.core import simtime
    from shadow_tpu.net.build import HostSpec, build
    from shadow_tpu.net.state import NetConfig

    topo_text = (bench.ref_topology_text() if args.topology == "ref"
                 else bench.ONE_VERTEX)

    def build_workload(seed, cap):
        """Returns (bundle, runner_kwargs, verify(sim) -> bool)."""
        H = args.hosts
        R = max(args.replicas, 1)
        if H % R:
            raise SystemExit(f"--replicas {R} must divide --hosts {H}")
        Hr = H // R   # hosts per replica block
        if args.workload == "phold":
            from shadow_tpu.apps import phold

            b = bench._build_phold(H, args.load, args.sim_seconds, seed,
                                   cap, graph=topo_text,
                                   replica_size=Hr if R > 1 else None)
            kw = dict(app_handlers=(phold.handler,),
                      app_bulk=None if args.no_bulk else phold.BULK)
            return b, kw, lambda sim: int(
                np.asarray(sim.app.rcvd).sum()) > 0
        if args.workload == "relay":
            from shadow_tpu.apps import relay

            hop = args.hop
            total = args.bytes   # bytes per circuit
            cfg = NetConfig(num_hosts=H, seed=seed,
                            end_time=args.sim_seconds * simtime.ONE_SECOND,
                            sockets_per_host=4, event_capacity=cap,
                            outbox_capacity=cap, router_ring=cap)
            hosts = [HostSpec(name=f"n{i}",
                              proc_start_time=simtime.ONE_SECOND)
                     for i in range(H)]
            b = build(cfg, topo_text, hosts)
            # circuits confined to replica blocks (ensemble mode:
            # identical chains per block, independent traffic)
            circuits = [
                [r * Hr + c * hop + k for k in range(hop)]
                for r in range(R) for c in range(Hr // hop)]
            b.sim = relay.setup(b.sim, circuits=circuits,
                                total_bytes=total)

            def verify(sim):
                rcvd = np.asarray(sim.app.rcvd)
                servers = np.asarray(sim.app.role) == relay.ROLE_SERVER
                verify.fraction = float(
                    np.minimum(rcvd[servers] / total, 1.0).mean())
                return bool((rcvd[servers] == total).all())

            kw = dict(app_handlers=(relay.handler,))
            if not args.no_bulk:
                kw["app_tcp_bulk"] = relay.TCP_BULK
                if args.bulk_lossless:
                    kw["tcp_bulk_lossless"] = True
            return b, kw, verify
        if args.workload == "tor":
            # shared-relay Tor shape (VERDICT r4 #2): 60% clients /
            # 30% relays / 10% servers; one 3-relay circuit per
            # client, relays drawn by consensus weight and shared up
            # to --slots circuits per host
            from shadow_tpu.apps import relay

            rng = np.random.default_rng(seed)
            chains = []
            for r in range(R):
                base = r * Hr
                n_cl = int(Hr * 0.6)
                n_rl = int(Hr * 0.3)
                chains += relay.consensus_circuits(
                    rng, n_circuits=n_cl,
                    clients=list(range(base, base + n_cl)),
                    relays=list(range(base + n_cl, base + n_cl + n_rl)),
                    servers=list(range(base + n_cl + n_rl, base + Hr)),
                    hops=3, max_slots=args.slots)
            total = args.bytes
            cfg = NetConfig(num_hosts=H, seed=seed,
                            end_time=args.sim_seconds * simtime.ONE_SECOND,
                            sockets_per_host=2 + 2 * args.slots,
                            event_capacity=cap, outbox_capacity=cap,
                            router_ring=cap,
                            out_ring=8)
            hosts = [HostSpec(name=f"n{i}",
                              proc_start_time=simtime.ONE_SECOND)
                     for i in range(H)]
            b = build(cfg, topo_text, hosts)
            b.sim = relay.setup_shared(b.sim, circuits=chains,
                                       total_bytes=total,
                                       max_slots=args.slots)
            n_chains = len(chains)

            def verify(sim):
                rcvd = np.asarray(sim.app.rcvd)
                got = float(rcvd.sum())
                want = float(n_chains * total)
                verify.fraction = min(got / want, 1.0) if want else 1.0
                return got == want

            kw = dict(app_handlers=(relay.mux_handler,))
            if not args.no_bulk:
                kw["app_tcp_bulk"] = relay.MUX_TCP_BULK
                if args.bulk_lossless:
                    kw["tcp_bulk_lossless"] = True
            return b, kw, verify
        # gossip
        from shadow_tpu.apps import gossip

        # block b is mined at t = b * interval (2 s); the last block
        # needs ~1 s of flood headroom before end_time, so the block
        # count is derived from the sim length (a fixed count would
        # make verification unsatisfiable for short runs)
        if args.sim_seconds < 5:
            raise SystemExit("gossip needs --sim-seconds >= 5")
        blocks = max(2, (args.sim_seconds - 3) // 2 + 1)
        if args.gossip_transport == "tcp":
            # the Bitcoin shape (r5): blocks ride persistent TCP peer
            # connections; single-shard, no replicas
            if R > 1:
                raise SystemExit("gossip tcp transport has no "
                                 "ensemble mode; use udp")
            cfg = NetConfig(num_hosts=H, seed=seed,
                            end_time=args.sim_seconds
                            * simtime.ONE_SECOND,
                            sockets_per_host=12, event_capacity=cap,
                            outbox_capacity=cap, router_ring=cap,
                            out_ring=16)
            hosts = [HostSpec(name=f"n{i}",
                              proc_start_time=simtime.ONE_SECOND)
                     for i in range(H)]
            b = build(cfg, topo_text, hosts)
            b.sim = gossip.setup_tcp(
                b.sim, peers_per_host=8,
                block_interval=2 * simtime.ONE_SECOND,
                max_blocks=blocks)

            def verify(sim):
                tips = np.asarray(sim.app.tip)
                verify.fraction = float((tips == blocks - 1).mean())
                return bool((tips == blocks - 1).all())

            return b, dict(app_handlers=(gossip.tcp_handler,)), verify
        cfg = NetConfig(num_hosts=H, seed=seed, tcp=False,
                        end_time=args.sim_seconds * simtime.ONE_SECOND,
                        event_capacity=cap, outbox_capacity=cap,
                        router_ring=cap, in_ring=32)
        hosts = [HostSpec(name=f"n{i}") for i in range(H)]
        b = build(cfg, topo_text, hosts)
        b.sim = gossip.setup(b.sim, peers_per_host=8,
                             block_interval=2 * simtime.ONE_SECOND,
                             max_blocks=blocks,
                             replica_size=Hr if R > 1 else None)

        def verify(sim):
            return bool(np.asarray(sim.app.tip == blocks - 1).all())

        return b, dict(app_handlers=(gossip.handler,)), verify

    def overflow_of(sim):
        return (int(jax.device_get(sim.events.overflow))
                + int(jax.device_get(sim.outbox.overflow))
                + int(jax.device_get(sim.net.rq_overflow)))

    # run tight, escalate on counted overflow (the bench.py pattern:
    # a clean overflow==0 pass at a tight capacity is sound AND fast;
    # each escalation costs one recompile)
    cap = args.cap or (0 if args.workload == "phold" else 64)
    for attempt in range(4):
        b, kw, verify = build_workload(args.seed, cap or None)
        if args.runahead:
            # raise-only: below the topology's honest minimum there is
            # no fidelity to regain, only more windows
            b.min_jump = max(b.min_jump,
                             args.runahead * simtime.ONE_MILLISECOND)
        if args.chunk and args.shards > 1:
            raise SystemExit(
                "--chunk is not implemented for the sharded runner; "
                "drop --shards or run monolithic (--chunk 0)")
        if args.chunk:
            from shadow_tpu.net.build import make_chunked_runner

            fn = make_chunked_runner(b, chunk_windows=args.chunk, **kw)
        else:
            fn = bench.make_shard_aware_runner(b, args.shards, **kw)

        t0 = time.perf_counter()
        sim, stats = fn(b.sim)
        jax.block_until_ready(stats.events_processed)
        compile_and_first = time.perf_counter() - t0
        if overflow_of(sim):
            cap = (cap or b.cfg.event_capacity) * 2
            print(f"# overflow at capacity {b.cfg.event_capacity}; "
                  f"retrying at {cap}", flush=True)
            continue

        # timed run on a distinct seed (see bench.py on result caching)
        b2, _, verify = build_workload(args.seed + 1, cap or None)
        jax.block_until_ready(b2.sim.net.rng_keys)
        t0 = time.perf_counter()
        sim, stats = fn(b2.sim)
        ev = int(jax.device_get(stats.events_processed))
        wall = time.perf_counter() - t0
        if not overflow_of(sim):
            break
        cap = (cap or b.cfg.event_capacity) * 2
        print(f"# overflow on timed seed at capacity "
              f"{b.cfg.event_capacity}; retrying at {cap}", flush=True)
    else:
        raise SystemExit("still overflowing after capacity escalation")

    # ONE resident sim state's device footprint (summing all live
    # arrays would also count the warmup build + inputs, ~3x over)
    dev_bytes = sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(sim)
        if hasattr(leaf, "nbytes"))
    ovf = overflow_of(sim)
    verified = verify(sim)
    fraction = getattr(verify, "fraction", 1.0 if verified else 0.0)
    print(json.dumps({
        **({"completion_fraction": round(fraction, 4)}
           if fraction < 1.0 else {}),
        "hosts": args.hosts,
        "workload": args.workload,
        **({"replicas": args.replicas} if args.replicas > 1 else {}),
        **({"runahead_ms": args.runahead} if args.runahead else {}),
        "topology": args.topology,
        "shards": args.shards,
        "platform": jax.devices()[0].platform,
        "events": ev,
        "wall_s": round(wall, 3),
        "events_per_sec": round(ev / wall, 1),
        "sim_sec_per_wall_sec": round(args.sim_seconds / wall, 3),
        "compile_s": round(compile_and_first - wall, 1),
        "device_bytes": dev_bytes,
        "overflow": ovf,
        "verified": verified,
    }))
    if not verified and args.allow_partial:
        return 0
    assert verified, "workload did not complete correctly"
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
